"""Proto3 wire codec for the node wire format.

The reference speaks gogo-protobuf on its query and import endpoints
(negotiated via ``Content-Type: application/x-protobuf``,
http/handler.go:1002) and for all node-to-node RPC (http/client.go).
This module implements the proto3 WIRE FORMAT directly — varints,
length-delimited fields, packed repeated scalars — against hand-written
schema tables whose field numbers mirror ``internal/public.proto``
(the numbers ARE the compatibility surface, like the roaring 12348
cookie), so byte streams interoperate with the reference's messages
without a generated-code dependency.

Schema table format: {field_number: (name, kind[, sub_schema])} with
kinds: ``uint``/``int``/``bool`` (varint; ``int`` carries negatives via
64-bit two's complement like proto3 int64), ``string``/``bytes``
(length-delimited), ``double`` (fixed 64-bit), ``msg`` (nested), and
``*``-suffixed repeated forms (scalars encode packed, decode accepts
packed or unpacked — proto3 rules).

Result type codes and attr type codes mirror
encoding/proto/proto.go:1057-1067 and attr.go:27-30.
"""

from __future__ import annotations

import struct

_U64 = (1 << 64) - 1

# ---------------------------------------------------------------- wire core


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(data: bytes, i: int) -> tuple[int, int]:
    n = shift = 0
    while True:
        if i >= len(data):
            raise ValueError("truncated varint")
        b = data[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, i
        shift += 7
        if shift >= 70:
            # 10 bytes max, like the vectorized packed decoder — an
            # 11th byte must reject identically on both paths (message
            # size must never decide accept vs reject)
            raise ValueError("varint too long")


def _key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


#: byte-loop <-> numpy crossover for packed repeated fields; below this
#: the ndarray setup costs more than it saves
_NP_PACKED_MIN = 1024


def _encode_packed_np(vals, signed: bool) -> bytes:
    """Packed-varint encode of a large int sequence, fully vectorized
    (the byte-at-a-time loop costs ~1 us/value; bulk imports carry
    millions).  Bit-identical to ``_varint`` over canonical values."""
    import numpy as np

    if signed:
        v = np.asarray(vals, dtype=np.int64).astype(np.uint64)
    else:
        v = np.asarray(vals, dtype=np.uint64)
    nb = np.ones(len(v), dtype=np.int64)
    x = v >> np.uint64(7)
    while x.any():  # <= 9 iterations (10-byte varints max)
        nb += (x != 0)
        x >>= np.uint64(7)
    ends = np.cumsum(nb)
    total = int(ends[-1])
    starts = ends - nb
    k = (np.arange(total, dtype=np.uint64)
         - np.repeat(starts, nb).astype(np.uint64))
    vrep = np.repeat(v, nb)
    out = ((vrep >> (np.uint64(7) * k)) & np.uint64(0x7F)).astype(np.uint8)
    is_last = np.zeros(total, dtype=bool)
    is_last[ends - 1] = True
    out[~is_last] |= 0x80
    return out.tobytes()


def _decode_packed_np(raw: bytes, signed: bool, arrays: bool = False):
    """Packed-varint decode of a large buffer, fully vectorized.
    Semantics match the byte loop with the 64-bit mask the wire
    implies (contributions land in disjoint 7-bit lanes, so the
    add-reduce below IS the bitwise OR of the loop)."""
    import numpy as np

    a = np.frombuffer(raw, dtype=np.uint8)
    cont = (a & 0x80) != 0
    ends = np.flatnonzero(~cont)
    if len(ends) == 0 or ends[-1] != len(a) - 1:
        raise ValueError("truncated varint")
    starts = np.empty_like(ends)
    starts[0] = 0
    starts[1:] = ends[:-1] + 1
    lens = ends - starts + 1
    if int(lens.max()) > 10:
        raise ValueError("varint too long")
    k = (np.arange(len(a), dtype=np.uint64)
         - np.repeat(starts, lens).astype(np.uint64))
    contrib = (a & 0x7F).astype(np.uint64) << (np.uint64(7) * k)
    vals = np.add.reduceat(contrib, starts)
    if signed:
        vals = vals.astype(np.int64)
    return vals if arrays else vals.tolist()


def _signed(n: int) -> int:
    """Decode a 64-bit varint as proto3 int64."""
    n &= _U64
    return n - (1 << 64) if n > (1 << 63) - 1 else n


# ------------------------------------------------------------ encode/decode


def encode(schema: dict, obj: dict) -> bytes:
    """Encode a plain dict against a schema table.  proto3 semantics:
    zero/empty/None values are not emitted."""
    out = bytearray()
    for field in sorted(schema):
        spec = schema[field]
        name, kind = spec[0], spec[1]
        v = obj.get(name)
        # proto3 default: omit zero/empty/False.  Sized values (lists,
        # strings, ndarrays — whose truthiness raises) check len().
        if v is None:
            continue
        if hasattr(v, "__len__"):
            if len(v) == 0:
                continue
        elif not v and v != 0.0:
            continue
        if kind == "uint" or kind == "bool":
            if int(v) == 0:
                continue
            out += _key(field, 0) + _varint(int(v))
        elif kind == "int":
            if int(v) == 0:
                continue
            out += _key(field, 0) + _varint(int(v) & _U64)
        elif kind == "double":
            if float(v) == 0.0:
                continue
            out += _key(field, 1) + struct.pack("<d", float(v))
        elif kind == "string":
            b = v.encode()
            out += _key(field, 2) + _varint(len(b)) + b
        elif kind == "bytes":
            out += _key(field, 2) + _varint(len(v)) + bytes(v)
        elif kind == "msg":
            b = encode(spec[2], v)
            out += _key(field, 2) + _varint(len(b)) + b
        elif kind == "uint*" or kind == "int*":
            if len(v) >= _NP_PACKED_MIN:
                try:
                    packed = _encode_packed_np(v, signed=(kind == "int*"))
                except OverflowError:
                    # a value outside [-(2^63), 2^64) — the loop's
                    # explicit mask handles it
                    packed = b"".join(_varint(int(x) & _U64) for x in v)
            else:
                packed = b"".join(_varint(int(x) & _U64) for x in v)
            out += _key(field, 2) + _varint(len(packed)) + packed
        elif kind == "string*":
            for s in v:
                b = s.encode()
                out += _key(field, 2) + _varint(len(b)) + b
        elif kind == "msg*":
            for m in v:
                b = encode(spec[2], m)
                out += _key(field, 2) + _varint(len(b)) + b
        else:  # pragma: no cover - schema author error
            raise ValueError(f"unknown kind {kind!r}")
    return bytes(out)


def _default(kind: str):
    if kind.endswith("*"):
        return []
    return {"uint": 0, "int": 0, "bool": False, "double": 0.0,
            "string": "", "bytes": b"", "msg": None}[kind]


def decode(schema: dict, data: bytes, arrays: bool = False) -> dict:
    """Decode bytes against a schema table; unknown fields are skipped
    (proto3 forward compatibility), absent fields read as defaults.

    ``arrays=True`` leaves LARGE packed uint*/int* fields as numpy
    int64/uint64 ndarrays instead of Python lists — the bulk-import
    endpoints opt in so 2M-element ID arrays flow to
    field.import_bits' vectorized grouping with zero list
    materialization.  Callers opting in must length-check with
    ``len(x)`` (ndarray truthiness raises)."""
    obj = {spec[0]: _default(spec[1]) for spec in schema.values()}
    i = 0
    while i < len(data):
        tag, i = _read_varint(data, i)
        field, wire = tag >> 3, tag & 7
        spec = schema.get(field)
        if wire == 0:
            n, i = _read_varint(data, i)
            if spec is None:
                continue
            name, kind = spec[0], spec[1]
            if kind == "bool":
                obj[name] = bool(n)
            elif kind == "int":
                obj[name] = _signed(n)
            elif kind == "int*" or kind == "uint*":
                # unpacked repeated occurrence; legal proto3 encoders
                # may mix it with packed chunks, so an ndarray from an
                # earlier arrays=True chunk converts back to plain ints
                if not isinstance(obj[name], list):
                    obj[name] = obj[name].tolist()
                obj[name].append(_signed(n) if kind == "int*" else n & _U64)
            elif kind == "uint":
                obj[name] = n & _U64
            else:
                raise ValueError(
                    f"field {field} wire type 0 does not match {kind!r}")
        elif wire == 1:
            if i + 8 > len(data):
                raise ValueError("truncated fixed64")
            raw = data[i:i + 8]
            i += 8
            if spec is not None:
                obj[spec[0]] = struct.unpack("<d", raw)[0]
        elif wire == 2:
            ln, i = _read_varint(data, i)
            if i + ln > len(data):
                raise ValueError("truncated length-delimited field")
            raw = data[i:i + ln]
            i += ln
            if spec is None:
                continue
            name, kind = spec[0], spec[1]
            if kind == "string":
                obj[name] = raw.decode()
            elif kind == "bytes":
                obj[name] = raw
            elif kind == "msg":
                obj[name] = decode(spec[2], raw)
            elif kind == "string*":
                obj[name].append(raw.decode())
            elif kind == "msg*":
                obj[name].append(decode(spec[2], raw))
            elif kind == "uint*" or kind == "int*":
                if ln >= _NP_PACKED_MIN:
                    decoded = _decode_packed_np(
                        raw, signed=(kind == "int*"), arrays=arrays)
                    if arrays and isinstance(obj[name], list) \
                            and not obj[name]:
                        obj[name] = decoded
                    else:
                        # second occurrence (packed fields may be
                        # split): degrade to a plain-int list —
                        # .tolist(), never list(ndarray), so no np
                        # scalars leak into JSON-bound payloads
                        if not isinstance(obj[name], list):
                            obj[name] = obj[name].tolist()
                        obj[name].extend(
                            decoded.tolist() if arrays else decoded)
                else:
                    if not isinstance(obj[name], list):
                        obj[name] = obj[name].tolist()
                    j = 0
                    while j < ln:
                        n, j = _read_varint(raw, j)
                        # mask like the vectorized path (proto3 64-bit
                        # wire semantics) so both sizes decode alike
                        obj[name].append(
                            _signed(n) if kind == "int*" else n & _U64)
            else:
                raise ValueError(
                    f"field {field} wire type 2 does not match {kind!r}")
        elif wire == 5:
            if i + 4 > len(data):
                raise ValueError("truncated fixed32")
            i += 4  # no fixed32 fields in this schema set; skip
        else:
            raise ValueError(f"unsupported wire type {wire}")
    return obj


# ------------------------------------------------- schemas (public.proto)

ATTR = {
    1: ("key", "string"),
    2: ("type", "uint"),
    3: ("stringValue", "string"),
    4: ("intValue", "int"),
    5: ("boolValue", "bool"),
    6: ("floatValue", "double"),
}

ROW = {
    1: ("columns", "uint*"),
    2: ("attrs", "msg*", ATTR),
    3: ("keys", "string*"),
}

ROW_IDENTIFIERS = {
    1: ("rows", "uint*"),
    2: ("keys", "string*"),
}

PAIR = {
    1: ("id", "uint"),
    2: ("count", "uint"),
    3: ("key", "string"),
}

FIELD_ROW = {
    1: ("field", "string"),
    2: ("rowID", "uint"),
    3: ("rowKey", "string"),
}

GROUP_COUNT = {
    1: ("group", "msg*", FIELD_ROW),
    2: ("count", "uint"),
}

VAL_COUNT = {
    1: ("val", "int"),
    2: ("count", "int"),
}

COLUMN_ATTR_SET = {
    1: ("id", "uint"),
    2: ("attrs", "msg*", ATTR),
    3: ("key", "string"),
}

QUERY_REQUEST = {
    1: ("query", "string"),
    2: ("shards", "uint*"),
    3: ("columnAttrs", "bool"),
    5: ("remote", "bool"),
    6: ("excludeRowAttrs", "bool"),
    7: ("excludeColumns", "bool"),
}

QUERY_RESULT = {
    1: ("row", "msg", ROW),
    2: ("n", "uint"),
    3: ("pairs", "msg*", PAIR),
    4: ("changed", "bool"),
    5: ("valCount", "msg", VAL_COUNT),
    6: ("type", "uint"),
    7: ("rowIDs", "uint*"),
    8: ("groupCounts", "msg*", GROUP_COUNT),
    9: ("rowIdentifiers", "msg", ROW_IDENTIFIERS),
}

QUERY_RESPONSE = {
    1: ("err", "string"),
    2: ("results", "msg*", QUERY_RESULT),
    3: ("columnAttrSets", "msg*", COLUMN_ATTR_SET),
}

IMPORT_REQUEST = {
    1: ("index", "string"),
    2: ("field", "string"),
    3: ("shard", "uint"),
    4: ("rowIDs", "uint*"),
    5: ("columnIDs", "uint*"),
    6: ("timestamps", "int*"),
    7: ("rowKeys", "string*"),
    8: ("columnKeys", "string*"),
}

IMPORT_VALUE_REQUEST = {
    1: ("index", "string"),
    2: ("field", "string"),
    3: ("shard", "uint"),
    5: ("columnIDs", "uint*"),
    6: ("values", "int*"),
    7: ("columnKeys", "string*"),
}

IMPORT_ROARING_VIEW = {
    1: ("name", "string"),
    2: ("data", "bytes"),
}

IMPORT_ROARING_REQUEST = {
    1: ("clear", "bool"),
    2: ("views", "msg*", IMPORT_ROARING_VIEW),
}

IMPORT_RESPONSE = {  # internal/private.proto ImportResponse
    1: ("err", "string"),
}

TRANSLATE_KEYS_REQUEST = {
    1: ("index", "string"),
    2: ("field", "string"),
    3: ("keys", "string*"),
}

TRANSLATE_KEYS_RESPONSE = {
    3: ("ids", "uint*"),
}

# result type codes (encoding/proto/proto.go:1057-1067)
TYPE_NIL = 0
TYPE_ROW = 1
TYPE_PAIRS = 2
TYPE_VAL_COUNT = 3
TYPE_UINT64 = 4
TYPE_BOOL = 5
TYPE_ROW_IDS = 6
TYPE_GROUP_COUNTS = 7
TYPE_ROW_IDENTIFIERS = 8
TYPE_PAIR = 9

# attr type codes (attr.go:27-30)
ATTR_STRING = 1
ATTR_INT = 2
ATTR_BOOL = 3
ATTR_FLOAT = 4


def attrs_to_proto(attrs: dict) -> list[dict]:
    out = []
    for k in sorted(attrs):
        v = attrs[k]
        if isinstance(v, bool):
            out.append({"key": k, "type": ATTR_BOOL, "boolValue": v})
        elif isinstance(v, int):
            out.append({"key": k, "type": ATTR_INT, "intValue": v})
        elif isinstance(v, float):
            out.append({"key": k, "type": ATTR_FLOAT, "floatValue": v})
        else:
            out.append({"key": k, "type": ATTR_STRING,
                        "stringValue": str(v)})
    return out


def proto_to_attrs(pb_attrs: list[dict]) -> dict:
    out = {}
    for a in pb_attrs:
        t = a["type"]
        if t == ATTR_BOOL:
            out[a["key"]] = a["boolValue"]
        elif t == ATTR_INT:
            out[a["key"]] = a["intValue"]
        elif t == ATTR_FLOAT:
            out[a["key"]] = a["floatValue"]
        else:
            out[a["key"]] = a["stringValue"]
    return out


# ----------------------------------------- result object <-> QueryResult


def result_to_proto(res) -> dict:
    """Executor result object -> QueryResult dict (the tagging logic of
    encoding/proto/proto.go:417-447)."""
    from pilosa_tpu.models.row import Row
    from pilosa_tpu.parallel.results import (
        GroupCount, Pair, PairField, ValCount,
    )

    if res is None:
        return {"type": TYPE_NIL}
    if isinstance(res, Row):
        row = {"attrs": attrs_to_proto(res.attrs or {})}
        if res.exclude_columns:
            pass
        elif res.keys:
            row["keys"] = list(res.keys)
        else:
            row["columns"] = [int(c) for c in res.columns()]
        return {"type": TYPE_ROW, "row": row}
    if isinstance(res, bool):
        return {"type": TYPE_BOOL, "changed": res}
    if isinstance(res, int):
        return {"type": TYPE_UINT64, "n": res}
    if isinstance(res, ValCount):
        return {"type": TYPE_VAL_COUNT,
                "valCount": {"val": int(res.val), "count": int(res.count)}}
    if isinstance(res, PairField):
        res = res.pair
    if isinstance(res, Pair):
        return {"type": TYPE_PAIR,
                "pairs": [_pair_to_proto(res)]}
    if isinstance(res, list):
        if res and isinstance(res[0], GroupCount):
            return {"type": TYPE_GROUP_COUNTS,
                    "groupCounts": [_group_count_to_proto(g) for g in res]}
        if res and isinstance(res[0], int):
            return {"type": TYPE_ROW_IDENTIFIERS,
                    "rowIdentifiers": {"rows": [int(r) for r in res]}}
        if res and isinstance(res[0], str):
            return {"type": TYPE_ROW_IDENTIFIERS,
                    "rowIdentifiers": {"keys": list(res)}}
        # TopN pair lists, including empty lists of any list kind
        pairs = []
        for p in res:
            if isinstance(p, PairField):
                p = p.pair
            pairs.append(_pair_to_proto(p))
        return {"type": TYPE_PAIRS, "pairs": pairs}
    raise TypeError(f"unserializable result type: {type(res)!r}")


def _pair_to_proto(p) -> dict:
    return {"id": int(p.id), "key": p.key or "", "count": int(p.count)}


def _group_count_to_proto(g) -> dict:
    return {
        "group": [
            {"field": fr.field, "rowID": int(fr.row_id),
             "rowKey": fr.row_key or ""}
            for fr in g.group
        ],
        "count": int(g.count),
    }


def proto_to_result(r: dict):
    """QueryResult dict -> the same objects the JSON path's
    deserialize_result produces, so remote protobuf partials feed the
    identical reduce paths."""
    from pilosa_tpu.models.row import Row
    from pilosa_tpu.parallel.results import (
        FieldRow, GroupCount, Pair, ValCount,
    )

    t = r["type"]
    if t == TYPE_NIL:
        return None
    if t == TYPE_ROW:
        pb = r["row"] or {}
        row = Row.from_columns(pb.get("columns") or [])
        row.keys = list(pb.get("keys") or [])
        row.attrs = proto_to_attrs(pb.get("attrs") or [])
        return row
    if t == TYPE_BOOL:
        return r["changed"]
    if t == TYPE_UINT64:
        return r["n"]
    if t == TYPE_VAL_COUNT:
        vc = r["valCount"] or {}
        return ValCount(val=vc.get("val", 0), count=vc.get("count", 0))
    if t == TYPE_PAIR:
        pairs = r["pairs"]
        p = pairs[0] if pairs else {"id": 0, "key": "", "count": 0}
        return Pair(id=p["id"], key=p["key"], count=p["count"])
    if t == TYPE_PAIRS:
        return [Pair(id=p["id"], key=p["key"], count=p["count"])
                for p in r["pairs"]]
    if t == TYPE_GROUP_COUNTS:
        return [
            GroupCount(
                group=[FieldRow(field=fr["field"], row_id=fr["rowID"],
                                row_key=fr["rowKey"])
                       for fr in g["group"]],
                count=g["count"],
            )
            for g in r["groupCounts"]
        ]
    if t == TYPE_ROW_IDENTIFIERS:
        ri = r["rowIdentifiers"] or {}
        return list(ri.get("keys") or []) or [int(x)
                                              for x in ri.get("rows") or []]
    if t == TYPE_ROW_IDS:
        return [int(x) for x in r["rowIDs"]]
    raise ValueError(f"unknown result type code {t}")
