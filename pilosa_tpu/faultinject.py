"""Failpoint registry: named fault-injection points compiled into the
production code paths.

Before this module, faults could only be injected through the test
transport fake (``LocalTransport.set_down``/``set_slow``) — the real
``InternalClient``/HTTP stack, the executor's per-shard map, the
compactor, the device dispatch funnel, and the result-cache fill path
had no way to fail on demand, so the failure-handling layer (circuit
breakers, hedged reads, partial-result degradation) could not be
exercised against the code that actually ships.  The design follows
the freebsd/etcd/pingcap failpoint idiom: sites are compiled in
permanently, and are **zero-cost when disarmed** — every site is
gated on the module-level ``armed`` bool, so the disarmed hot path
pays one attribute load and a falsy test (benchmarked in bench.py
extras.faultinject, same <1% budget as the observe/admission gates).

Arming surfaces (all feeding :func:`arm`):

- ``[faultinject] armed = "<spec>"`` config / the
  ``PILOSA_TPU_FAULTINJECT_ARMED`` env var (via config.py), applied by
  the server assembly at construction and disarmed at close;
- ``POST /debug/failpoints`` with ``{"arm": "<spec>"}`` /
  ``{"disarm": "<name>"|true}`` (server/handler.py) — the live ops
  surface ``tools/loadgen.py --chaos`` drives on a schedule.

Spec grammar (deterministic by construction — no randomness, so a
chaos run replays exactly)::

    spec   := point (";" point)*
    point  := name "=" action
    action := kind ["*" max] ["@" every]
    kind   := "error" | "error(" cls ")" | "delay(" ms ")"
    cls    := "fail" | "transport" | "oom" | "shed"

``*max`` fires the action at most ``max`` times (then the point stays
listed with its counters but stops triggering); ``@every`` fires on
every ``every``-th call only (1st, (every+1)-th, ...).  Examples::

    client.request.send=error(transport)*3
    executor.map_shard=delay(50)@2
    device.dispatch=error(oom)*1

Known sites (``SITES``) — arming an unknown name is a ValueError so a
typo cannot silently arm nothing.
"""

from __future__ import annotations

import threading
import time

#: The compiled-in failpoint sites.  Adding a site means adding the
#: ``hit()`` call at the code path AND the name here.
SITES: dict[str, str] = {
    "client.request.send":
        "InternalClient._request, before the request goes on the wire",
    "client.request.recv":
        "InternalClient._request, after the response body is read",
    "executor.map_shard":
        "Executor local per-shard map, before each shard evaluates",
    "admission.acquire":
        "AdmissionController.acquire, before the gate decides — "
        "error(shed) injects a deterministic refusal, delay(ms) a "
        "queue-delay stall",
    "replica.write":
        "Executor._replicate_to_shard_owners, before each remote "
        "delivery",
    "compactor.merge":
        "ingest.Compactor.run_once, before each fragment's delta merge",
    "device.dispatch":
        "ops.bitmap.note_dispatch — every device kernel launch",
    "resultcache.fill":
        "runtime.ResultCache.put, before a computed result is cached",
    "residency.promote":
        "runtime.residency promotion worker, before a host-tier entry "
        "is placed back on device (error = promotion failure -> the "
        "waiting query takes the host-compute fallback; delay(ms) = a "
        "tier stall)",
    "hint.replay":
        "parallel.hints replay worker, before each queued hint is "
        "delivered to its healed peer (errors leave the hint queued "
        "for the next backoff scan; delay(ms) = a slow drain)",
}


class FailpointError(RuntimeError):
    """The default injected error (kind ``error`` / ``error(fail)``)."""


class ResourceExhaustedError(RuntimeError):
    """Injected device-OOM lookalike (kind ``error(oom)``): the message
    carries the backend's RESOURCE_EXHAUSTED marker, so the executor's
    evict-and-retry path treats it exactly like a real XLA allocation
    failure."""

    def __init__(self, name: str):
        super().__init__(
            f"RESOURCE_EXHAUSTED: injected by failpoint {name!r}")


def _error_classes():
    # lazy: faultinject must import without dragging the cluster layer
    from pilosa_tpu.parallel.cluster import ShedByPeerError, TransportError

    return {
        "fail": lambda name: FailpointError(
            f"injected by failpoint {name!r}"),
        "transport": lambda name: TransportError(
            f"node unreachable: injected by failpoint {name!r}"),
        "shed": lambda name: ShedByPeerError(
            f"shed by peer: injected by failpoint {name!r}", 503),
        "oom": ResourceExhaustedError,
    }


class _Failpoint:
    """One armed point.  Trigger bookkeeping happens under the module
    lock; the action itself (raise / sleep) runs OUTSIDE it, so an
    injected delay can never hold the registry lock."""

    __slots__ = ("name", "spec", "kind", "arg", "max_triggers", "every",
                 "calls", "triggers")

    def __init__(self, name: str, spec: str):
        self.name = name
        self.spec = spec
        self.calls = 0
        self.triggers = 0
        action = spec
        self.max_triggers = 0  # 0 = unlimited
        self.every = 1
        if "@" in action:
            action, _, every = action.partition("@")
            self.every = int(every)
            if self.every < 1:
                raise ValueError(f"failpoint {name}: @every must be >= 1")
        if "*" in action:
            action, _, mx = action.partition("*")
            self.max_triggers = int(mx)
            if self.max_triggers < 1:
                raise ValueError(f"failpoint {name}: *max must be >= 1")
        action = action.strip()
        if action.startswith("delay(") and action.endswith(")"):
            self.kind = "delay"
            self.arg = float(action[len("delay("):-1]) / 1e3  # ms -> s
            if self.arg < 0:
                raise ValueError(f"failpoint {name}: negative delay")
        elif action == "error":
            self.kind = "error"
            self.arg = "fail"
        elif action.startswith("error(") and action.endswith(")"):
            self.kind = "error"
            self.arg = action[len("error("):-1].strip()
            if self.arg not in ("fail", "transport", "shed", "oom"):
                raise ValueError(
                    f"failpoint {name}: unknown error class "
                    f"{self.arg!r} (fail|transport|shed|oom)")
        else:
            raise ValueError(
                f"failpoint {name}: unparsable action {spec!r} "
                "(error | error(cls) | delay(ms), with optional "
                "*max and @every)")

    def decide_locked(self) -> tuple[str, object] | None:
        """Caller holds the module lock.  Returns (kind, arg) when this
        call should trigger, else None."""
        self.calls += 1
        if self.max_triggers and self.triggers >= self.max_triggers:
            return None
        if (self.calls - 1) % self.every != 0:
            return None
        self.triggers += 1
        return (self.kind, self.arg)

    def snapshot_locked(self) -> dict:
        return {"spec": self.spec, "calls": self.calls,
                "triggers": self.triggers,
                "exhausted": bool(self.max_triggers
                                  and self.triggers >= self.max_triggers)}


from pilosa_tpu import lockcheck as _lockcheck

# module-level, so the dynamic checker only wraps it in env-var mode
# (PILOSA_TPU_LOCKCHECK=1 at process start); hit() never takes any
# other lock, so no ordering edge can originate here
_lock = _lockcheck.lock("faultinject")
_points: dict[str, _Failpoint] = {}

#: The one-word fast gate every site reads BEFORE calling hit():
#: ``if faultinject.armed: faultinject.hit(name)``.  Updated (under
#: the lock) whenever the registry changes; a momentarily stale read
#: costs one extra dict probe or skips one injection window — never a
#: wrong result.
armed = False


def parse_spec(spec: str) -> dict[str, _Failpoint]:
    """Parse ``name=action;name=action`` into failpoints; validates
    both names and actions before anything arms (all-or-nothing)."""
    out: dict[str, _Failpoint] = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        name, eq, action = part.partition("=")
        name = name.strip()
        if not eq or not action.strip():
            raise ValueError(f"bad failpoint entry {part!r} "
                             "(expected name=action)")
        if name not in SITES:
            raise ValueError(
                f"unknown failpoint {name!r}; known sites: "
                f"{', '.join(sorted(SITES))}")
        out[name] = _Failpoint(name, action.strip())
    return out


def arm(spec: str) -> list[str]:
    """Arm every point in ``spec`` (replacing any existing arming of
    the same names; other armed points stay).  Returns the armed
    names.  Raises ValueError on any unknown name or malformed action
    without arming anything."""
    global armed
    parsed = parse_spec(spec)
    with _lock:
        _points.update(parsed)
        armed = bool(_points)
    _journal("failpoint.arm", points=sorted(parsed))
    return sorted(parsed)


def disarm(name: str | None = None) -> None:
    """Disarm one point, or all of them (``name=None``)."""
    global armed
    with _lock:
        if name is None:
            _points.clear()
        else:
            _points.pop(name, None)
        armed = bool(_points)
    _journal("failpoint.disarm",
             points=[name] if name is not None else [])


def _journal(kind: str, **fields) -> None:
    """Arming/disarming chaos is exactly the state change a merged
    cluster timeline must show next to the failures it caused.  Lazy
    import (observe is a higher layer) and AFTER ``_lock`` is released
    — the journal takes its own lock."""
    from pilosa_tpu import observe as _observe

    if _observe.journal_on:
        _observe.emit(kind, **fields)


def hit(name: str) -> None:
    """One pass through the failpoint ``name``.  Call sites gate on
    the module ``armed`` bool first, so the disarmed cost never
    exceeds one attribute read; this function is only reached while
    something is armed."""
    with _lock:
        p = _points.get(name)
        action = p.decide_locked() if p is not None else None
    if action is None:
        return
    kind, arg = action
    if kind == "delay":
        time.sleep(arg)
        return
    raise _error_classes()[arg](name)


def snapshot() -> dict:
    """The /debug/failpoints document."""
    with _lock:
        points = {n: p.snapshot_locked()
                  for n, p in sorted(_points.items())}
        total = sum(p["triggers"] for p in points.values())
    return {
        "armed": bool(points),
        "points": points,
        "triggers": total,
        "sites": dict(sorted(SITES.items())),
    }


def publish_gauges(stats) -> None:
    """failpoint.* gauge family for /metrics and /debug/vars —
    published unconditionally (zeros on a clean server) so the family
    is scrape-visible before the first chaos run."""
    with _lock:
        n = len(_points)
        total = sum(p.triggers for p in _points.values())
    stats.gauge("failpoint.armed", n)
    stats.gauge("failpoint.triggers", total)
