"""PQL AST: Query -> Call tree with typed argument accessors.

Parity with the reference's pql/ast.go: Call{Name, Args, Children},
Condition{Op, Value}, and a String() form that round-trips through the
parser (used for node-to-node query forwarding, executor.go:2414).
"""

from __future__ import annotations

import datetime as _dt

# Mutating call names (reference Query.WriteCallN, pql/ast.go:116 and
# executor write routing).
WRITE_CALLS = frozenset(
    ["Set", "Clear", "SetRowAttrs", "SetColumnAttrs", "ClearRow", "Store"]
)

# Condition operator tokens in canonical string form.
COND_OPS = ("><", "<=", ">=", "==", "!=", "<", ">")


class Condition:
    """A comparison attached to a field argument: ``field <op> value``.
    Op is one of <, <=, >, >=, ==, !=, >< (between)."""

    __slots__ = ("op", "value")

    def __init__(self, op: str, value):
        if op not in COND_OPS:
            raise ValueError(f"invalid condition op: {op}")
        self.op = op
        self.value = value

    def int_slice_value(self) -> list[int]:
        """Between bounds as ints (reference IntSliceValue, pql/ast.go:495)."""
        if not isinstance(self.value, list):
            raise ValueError(f"expected list value, got {self.value!r}")
        out = []
        for v in self.value:
            if isinstance(v, bool) or not isinstance(v, int):
                raise ValueError(f"unexpected value in condition list: {v!r}")
            out.append(v)
        return out

    def __str__(self) -> str:
        return f"{self.op} {format_value(self.value)}"

    def __repr__(self) -> str:
        return f"Condition({self.op!r}, {self.value!r})"

    def __eq__(self, other):
        return (
            isinstance(other, Condition)
            and self.op == other.op
            and self.value == other.value
        )


def format_value(v) -> str:
    if isinstance(v, str):
        return '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return "null"
    if isinstance(v, list):
        return "[" + ",".join(format_value(x) for x in v) + "]"
    if isinstance(v, _dt.datetime):
        return f'"{v.strftime("%Y-%m-%dT%H:%M")}"'
    if isinstance(v, Condition):
        return str(v)
    if isinstance(v, Call):
        return str(v)
    return str(v)


class Call:
    """One PQL call: ``Name(child1, child2, key=value, ...)``."""

    __slots__ = ("name", "args", "children")

    def __init__(self, name: str, args: dict | None = None, children: list | None = None):
        self.name = name
        self.args: dict = args or {}
        self.children: list[Call] = children or []

    # ---- typed accessors (reference pql/ast.go:272-392) ----

    def field_arg(self) -> str:
        """The single field=row style argument's key (reference FieldArg:
        used by Set/Clear where the arg map holds field->row).  Reserved
        arg names ("from"/"to" on time-range Row) are never field args —
        and arg order is not significant after a String() round-trip."""
        for k in self.args:
            if not k.startswith("_") and k not in ("from", "to"):
                return k
        raise ValueError(f"{self.name}() requires a field argument")

    def uint_arg(self, key: str):
        v = self.args.get(key)
        if v is None:
            return None
        if isinstance(v, bool) or not isinstance(v, int) or v < 0:
            raise ValueError(f"{self.name}() arg {key!r} must be a non-negative integer")
        return v

    def int_arg(self, key: str):
        v = self.args.get(key)
        if v is None:
            return None
        if isinstance(v, bool) or not isinstance(v, int):
            raise ValueError(f"{self.name}() arg {key!r} must be an integer")
        return v

    def bool_arg(self, key: str):
        v = self.args.get(key)
        if v is None:
            return None
        if not isinstance(v, bool):
            raise ValueError(f"{self.name}() arg {key!r} must be a boolean")
        return v

    def string_arg(self, key: str):
        v = self.args.get(key)
        if v is None:
            return None
        if not isinstance(v, str):
            raise ValueError(f"{self.name}() arg {key!r} must be a string")
        return v

    def uint_slice_arg(self, key: str):
        v = self.args.get(key)
        if v is None:
            return None
        if not isinstance(v, list):
            raise ValueError(f"{self.name}() arg {key!r} must be a list")
        out = []
        for x in v:
            if isinstance(x, bool) or not isinstance(x, int) or x < 0:
                raise ValueError(f"{self.name}() arg {key!r} must hold unsigned ints")
            out.append(x)
        return out

    def call_arg(self, key: str):
        v = self.args.get(key)
        if v is None:
            return None
        if not isinstance(v, Call):
            raise ValueError(f"{self.name}() arg {key!r} must be a call")
        return v

    def condition_arg(self):
        """(field, Condition) for the single condition argument, if any."""
        for k, v in self.args.items():
            if isinstance(v, Condition):
                return k, v
        return None

    def has_condition_arg(self) -> bool:
        return any(isinstance(v, Condition) for v in self.args.values())

    def is_write(self) -> bool:
        return self.name in WRITE_CALLS

    def clone(self) -> "Call":
        return Call(
            self.name,
            dict(self.args),
            [c.clone() for c in self.children],
        )

    def __str__(self) -> str:
        parts = [str(c) for c in self.children]
        for key in sorted(self.args):
            v = self.args[key]
            if isinstance(v, Condition):
                parts.append(f"{key} {v}")
            else:
                parts.append(f"{key}={format_value(v)}")
        return f"{self.name or '!UNNAMED'}({', '.join(parts)})"

    def __repr__(self) -> str:
        return f"Call({str(self)!r})"

    def __eq__(self, other):
        return (
            isinstance(other, Call)
            and self.name == other.name
            and self.args == other.args
            and self.children == other.children
        )


class Query:
    """A parsed PQL query: a sequence of calls."""

    __slots__ = ("calls",)

    def __init__(self, calls: list[Call] | None = None):
        self.calls = calls or []

    def write_call_n(self) -> int:
        """Number of mutating calls (reference WriteCallN, pql/ast.go:116)."""
        return sum(1 for c in self.calls if c.is_write())

    def __str__(self) -> str:
        return "".join(str(c) for c in self.calls)

    def __repr__(self) -> str:
        return f"Query({str(self)!r})"
