"""ctypes binding for the native C++ PQL parser (libpql).

SURVEY.md §7 native component 3: a C++ parser shared by the server and
clients so parsing stays off Python in the query hot path.  The .so is
built lazily from pilosa_tpu/native/pql_parser.cpp with g++ (same
pattern as the roaring codec); when the toolchain is unavailable the
Python parser in pilosa_tpu.pql.parser serves as the fallback — both
accept the identical language and are differential-tested against each
other (tests/test_pql_native.py)."""

from __future__ import annotations

import ctypes
import json
import os

from pilosa_tpu.native_loader import NativeLib
from pilosa_tpu.pql.ast import Call, Condition, Query
from pilosa_tpu.pql.parser import ParseError

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")


def _setup(lib) -> None:
    lib.pql_parse.argtypes = [ctypes.c_char_p]
    lib.pql_parse.restype = ctypes.c_void_p
    lib.pql_free.argtypes = [ctypes.c_void_p]
    lib.pql_free.restype = None


_NATIVE = NativeLib(
    src=os.path.join(_NATIVE_DIR, "pql_parser.cpp"),
    so=os.path.join(_NATIVE_DIR, "build", "libpql.so"),
    setup=_setup,
)


def available() -> bool:
    return _NATIVE.available()


def _load():
    return _NATIVE.load()


def parse_native(src: str) -> Query:
    """Parse via libpql; raises ParseError on syntax errors and
    RuntimeError when the native library is unavailable."""
    if "\x00" in src:
        # NUL truncates at the c_char_p boundary — reject, like parse()
        raise ParseError("NUL byte in query", src, src.index("\x00"))
    lib = _load()
    if lib is None:
        raise RuntimeError("native PQL parser unavailable")
    ptr = lib.pql_parse(src.encode())
    try:
        raw = ctypes.string_at(ptr).decode()
    finally:
        lib.pql_free(ptr)
    d = json.loads(raw)
    if "error" in d:
        raise ParseError(d["error"], src, d.get("pos", 0))
    return Query([_call_from_json(c) for c in d["calls"]])


def _call_from_json(d: dict) -> Call:
    return Call(
        d["name"],
        {k: _value_from_json(v) for k, v in d["args"].items()},
        [_call_from_json(c) for c in d["children"]],
    )


def _value_from_json(v):
    if isinstance(v, dict):
        if "$cond" in v:
            c = v["$cond"]
            return Condition(c["op"], _value_from_json(c["value"]))
        if "$call" in v:
            return _call_from_json(v["$call"])
    if isinstance(v, list):
        return [_value_from_json(x) for x in v]
    return v
