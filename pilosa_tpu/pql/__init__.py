"""PQL: the Pilosa Query Language parser and AST.

Same language surface as the reference's pql/ package (grammar:
pql/pql.peg; AST: pql/ast.go), implemented as a hand-written
recursive-descent parser instead of a generated packrat PEG parser.
"""

import os as _os

from pilosa_tpu.pql.ast import Call, Condition, Query, WRITE_CALLS
from pilosa_tpu.pql.parser import parse as parse_python, ParseError

# The C++ parser (libpql) is preferred when its toolchain is available;
# PILOSA_TPU_NATIVE_PQL=0 forces the Python parser.  Both accept the
# identical language (differential-tested in tests/test_pql_native.py).
_USE_NATIVE = _os.environ.get("PILOSA_TPU_NATIVE_PQL", "1") != "0"


def _reject_internal(call, src: str) -> None:
    """Refuse the executor's sentinel spellings (_Empty/_Noop/
    _EmptyRows — or any underscore-prefixed call) outside remote
    semantics: they are the key-translation layer's node-to-node wire
    detail, not public query surface.  Trust boundary caveat: the
    ``remote`` flag itself is client-asserted (the reference's model —
    there is no peer authentication), so this gate keeps sentinels out
    of the ORDINARY query surface and blocks accidental/naive use; a
    client that deliberately asserts remote semantics also accepts
    remote behavior (no translation, no cluster fan-out)."""
    if call.name.startswith("_"):
        raise ParseError(f"unknown call: {call.name}", src, 0)
    for child in call.children:
        _reject_internal(child, src)
    # the grammar admits Call values under ANY argument key and inside
    # list args (parser.item's nested-call branch), not just the
    # GroupBy "filter" slot — walk them all, or a sentinel smuggled as
    # e.g. Row(f=_Empty()) would slip the gate
    for v in call.args.values():
        if isinstance(v, Call):
            _reject_internal(v, src)
        elif isinstance(v, list):
            for item in v:
                if isinstance(item, Call):
                    _reject_internal(item, src)


def parse(src: str, allow_internal: bool = True) -> Query:
    """Parse a PQL string into a Query (reference pql.ParseString).
    ``allow_internal=False`` (the public, non-remote surface) rejects
    underscore-prefixed call names uniformly across both parser
    engines."""
    q = None
    if _USE_NATIVE:
        from pilosa_tpu.pql import native

        if native.available():
            q = native.parse_native(src)
    if q is None:
        q = parse_python(src)
    if not allow_internal:
        for call in q.calls:
            _reject_internal(call, src)
    return q


__all__ = [
    "Call",
    "Condition",
    "Query",
    "WRITE_CALLS",
    "parse",
    "parse_python",
    "ParseError",
]
