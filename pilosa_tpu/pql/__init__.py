"""PQL: the Pilosa Query Language parser and AST.

Same language surface as the reference's pql/ package (grammar:
pql/pql.peg; AST: pql/ast.go), implemented as a hand-written
recursive-descent parser instead of a generated packrat PEG parser.
"""

import os as _os

from pilosa_tpu.pql.ast import Call, Condition, Query, WRITE_CALLS
from pilosa_tpu.pql.parser import parse as parse_python, ParseError

# The C++ parser (libpql) is preferred when its toolchain is available;
# PILOSA_TPU_NATIVE_PQL=0 forces the Python parser.  Both accept the
# identical language (differential-tested in tests/test_pql_native.py).
_USE_NATIVE = _os.environ.get("PILOSA_TPU_NATIVE_PQL", "1") != "0"


def parse(src: str) -> Query:
    """Parse a PQL string into a Query (reference pql.ParseString)."""
    if _USE_NATIVE:
        from pilosa_tpu.pql import native

        if native.available():
            return native.parse_native(src)
    return parse_python(src)


__all__ = [
    "Call",
    "Condition",
    "Query",
    "WRITE_CALLS",
    "parse",
    "parse_python",
    "ParseError",
]
