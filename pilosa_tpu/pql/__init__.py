"""PQL: the Pilosa Query Language parser and AST.

Same language surface as the reference's pql/ package (grammar:
pql/pql.peg; AST: pql/ast.go), implemented as a hand-written
recursive-descent parser instead of a generated packrat PEG parser.
"""

from pilosa_tpu.pql.ast import Call, Condition, Query, WRITE_CALLS
from pilosa_tpu.pql.parser import parse, ParseError

__all__ = ["Call", "Condition", "Query", "WRITE_CALLS", "parse", "ParseError"]
