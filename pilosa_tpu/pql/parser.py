"""Hand-written recursive-descent PQL parser.

Accepts the same language as the reference's PEG grammar (pql/pql.peg),
including the special call forms (Set/SetRowAttrs/SetColumnAttrs/Clear/
ClearRow/Store/TopN/Rows/Range), conditions (``field <= 10``), the
``a < field <= b`` conditional sugar (lowered to a BETWEEN condition with
strict bounds adjusted by one, pql/ast.go:81-103), lists, timestamps, and
quoted strings.  Implemented by hand instead of a generated packrat
parser — ~10x less code and no generation step.
"""

from __future__ import annotations

import re

from pilosa_tpu.pql.ast import Call, Condition, Query

# re.ASCII everywhere: the reference grammar is ASCII [0-9] (pql.peg);
# without it Python's \d admits Unicode digits the native parser
# (and the reference) reject.
_TIMESTAMP_RE = re.compile(r"\d{4}-[01]\d-[0-3]\dT\d\d:\d\d", re.ASCII)
#: leading underscore admits the executor's internal sentinel calls
#: (_Empty/_Noop/_EmptyRows, substituted for missing keys during
#: translation) — their String() form must re-parse on remote nodes
#: (remote scatter re-parses the translated tree; a replica reading a
#: key that does not exist yet scatters such a tree)
_IDENT_RE = re.compile(r"[A-Za-z_][A-Za-z0-9]*")
_FIELD_RE = re.compile(r"[A-Za-z][A-Za-z0-9_-]*")
_BARE_STR_RE = re.compile(r"[A-Za-z0-9:_-]+", re.ASCII)
_NUMBER_RE = re.compile(r"-?(?:\d+(?:\.\d*)?|\.\d+)", re.ASCII)
_UINT_RE = re.compile(r"\d+", re.ASCII)
_INT_RE = re.compile(r"-?\d+", re.ASCII)

# Reserved positional argument keys (pql.peg `reserved`).
RESERVED = {"_row", "_col", "_start", "_end", "_timestamp", "_field"}


def _is_ascii_digit(c: str) -> bool:
    return len(c) == 1 and "0" <= c <= "9"


def _is_ascii_alnum(c: str) -> bool:
    return len(c) == 1 and (
        ("a" <= c <= "z") or ("A" <= c <= "Z") or ("0" <= c <= "9"))


class ParseError(ValueError):
    def __init__(self, message: str, src: str, pos: int):
        line = src.count("\n", 0, pos) + 1
        col = pos - (src.rfind("\n", 0, pos) + 1) + 1
        super().__init__(f"{message} at line {line}, char {col}")
        self.pos = pos


# Maximum call-nesting depth — matches the native parser's MAX_DEPTH so
# both reject the same pathological inputs with ParseError instead of
# RecursionError / stack overflow.
MAX_DEPTH = 128


class _Parser:
    def __init__(self, src: str):
        self.src = src
        self.pos = 0
        self.depth = 0

    # ------------------------------------------------------------ plumbing

    def error(self, message: str) -> ParseError:
        return ParseError(message, self.src, self.pos)

    def sp(self) -> None:
        while self.pos < len(self.src) and self.src[self.pos] in " \t\n":
            self.pos += 1

    def peek(self) -> str:
        return self.src[self.pos] if self.pos < len(self.src) else ""

    def literal(self, text: str) -> bool:
        if self.src.startswith(text, self.pos):
            self.pos += len(text)
            return True
        return False

    def expect(self, text: str) -> None:
        if not self.literal(text):
            raise self.error(f"expected {text!r}")

    def match(self, regex: re.Pattern) -> str | None:
        m = regex.match(self.src, self.pos)
        if m is None:
            return None
        self.pos = m.end()
        return m.group(0)

    def comma(self) -> bool:
        save = self.pos
        self.sp()
        if self.literal(","):
            self.sp()
            return True
        self.pos = save
        return False

    def open(self) -> None:
        self.expect("(")
        self.sp()

    def close(self) -> None:
        self.sp()
        self.expect(")")
        self.sp()

    # ------------------------------------------------------------- strings

    def quoted_string(self) -> str | None:
        q = self.peek()
        if q not in "'\"":
            return None
        self.pos += 1
        out = []
        while True:
            c = self.peek()
            if c == "":
                raise self.error("unterminated string")
            if c == "\\" and self.pos + 1 < len(self.src) and self.src[self.pos + 1] in (q, "\\"):
                out.append(self.src[self.pos + 1])
                self.pos += 2
                continue
            if c == q:
                self.pos += 1
                return "".join(out)
            out.append(c)
            self.pos += 1

    def timestamp_fmt(self) -> str | None:
        """Bare or quoted YYYY-MM-DDTHH:MM."""
        save = self.pos
        q = self.peek()
        if q in "'\"":
            self.pos += 1
            ts = self.match(_TIMESTAMP_RE)
            if ts is not None and self.literal(q):
                return ts
            self.pos = save
            return None
        ts = self.match(_TIMESTAMP_RE)
        if ts is not None:
            return ts
        self.pos = save
        return None

    # -------------------------------------------------------------- values

    def value(self):
        if self.literal("["):
            self.sp()
            items = []
            if not self._at_rbrack():
                items.append(self.item())
                while self.comma():
                    items.append(self.item())
            self.sp()
            self.expect("]")
            self.sp()
            return items
        return self.item()

    def _at_rbrack(self) -> bool:
        save = self.pos
        self.sp()
        at = self.peek() == "]"
        self.pos = save
        return at

    def _keyword_guard_ok(self) -> bool:
        """After null/true/false the grammar requires comma or close
        (pql.peg `item`)."""
        save = self.pos
        self.sp()
        ok = self.peek() in ",)"
        self.pos = save
        return ok

    def item(self):
        for kw, val in (("null", None), ("true", True), ("false", False)):
            save = self.pos
            if self.literal(kw):
                if self._keyword_guard_ok():
                    return val
                self.pos = save
        ts = self.timestamp_fmt()
        if ts is not None:
            return ts
        # number (must not run into an identifier tail)
        save = self.pos
        num = self.match(_NUMBER_RE)
        if num is not None:
            if not (_is_ascii_alnum(self.peek()) or self.peek() in "_:-"):
                if "." in num:
                    return float(num)
                return int(num)
            self.pos = save
        # nested call
        save = self.pos
        ident = self.match(_IDENT_RE)
        if ident is not None:
            self.sp()
            if self.peek() == "(":
                self.pos = save
                return self.call()
            self.pos = save
        bare = self.match(_BARE_STR_RE)
        if bare is not None:
            return bare
        s = self.quoted_string()
        if s is not None:
            return s
        raise self.error("expected value")

    # ---------------------------------------------------------------- args

    def field_name(self) -> str:
        name = self.match(_FIELD_RE)
        if name is None:
            for r in RESERVED:
                if self.literal(r):
                    return r
            raise self.error("expected field name")
        return name

    def cond_op(self) -> str | None:
        for op in ("><", "<=", ">=", "==", "!=", "<", ">"):
            if self.literal(op):
                return op
        return None

    def arg_into(self, args: dict) -> None:
        # conditional sugar: int <[=] field <[=] int
        if _is_ascii_digit(self.peek()) or (
            self.peek() == "-" and self.pos + 1 < len(self.src)
            and _is_ascii_digit(self.src[self.pos + 1])
        ):
            low = int(self.match(_INT_RE))
            self.sp()
            op1 = "<=" if self.literal("<=") else ("<" if self.literal("<") else None)
            if op1 is None:
                raise self.error("expected < or <= in conditional")
            self.sp()
            field = self.field_name()
            self.sp()
            op2 = "<=" if self.literal("<=") else ("<" if self.literal("<") else None)
            if op2 is None:
                raise self.error("expected < or <= in conditional")
            self.sp()
            high = int(self.match(_INT_RE))
            # strict bounds tighten by one (reference endConditional,
            # pql/ast.go:89-95)
            if op1 == "<":
                low += 1
            if op2 == "<":
                high -= 1
            args[field] = Condition("><", [low, high])
            return
        field = self.field_name()
        self.sp()
        # condition ops first: "==" must win over "=".
        op = self.cond_op()
        if op is not None:
            self.sp()
            args[field] = Condition(op, self.value())
            return
        if self.literal("="):
            self.sp()
            args[field] = self.value()
            return
        raise self.error(f"expected = or condition operator after {field!r}")

    def args_into(self, args: dict) -> None:
        self.arg_into(args)
        while True:
            save = self.pos
            if not self.comma():
                return
            try:
                self.arg_into(args)
            except ParseError:
                self.pos = save
                return

    # ---------------------------------------------------------------- calls

    def _pos_uint_or_str(self, key: str, args: dict) -> None:
        num = self.match(_UINT_RE)
        if num is not None:
            args[key] = int(num)
            return
        s = self.quoted_string()
        if s is not None:
            args[key] = s
            return
        raise self.error(f"expected integer or quoted key for {key}")

    def call(self) -> Call:
        self.depth += 1
        try:
            if self.depth > MAX_DEPTH:
                raise self.error("query too deeply nested")
            return self._call_dispatch()
        finally:
            self.depth -= 1

    def _call_dispatch(self) -> Call:
        name = self.match(_IDENT_RE)
        if name is None:
            raise self.error("expected call name")
        self.sp()
        handler = getattr(self, f"_call_{name}", None)
        if handler is not None:
            save = self.pos
            try:
                return handler()
            except ParseError:
                # PEG ordered choice: a special form that fails to match
                # falls through to the generic IDENT(allargs) rule — this is
                # how String()-serialized calls (TopN(_field="f", ...))
                # re-parse on remote nodes (executor.go:2414).
                self.pos = save
        return self._generic_call(name)

    def _generic_call(self, name: str) -> Call:
        call = Call(name)
        self.open()
        self._allargs_into(call)
        self.comma()  # tolerate trailing comma (grammar: comma? close)
        self.close()
        return call

    def _call_Set(self) -> Call:
        call = Call("Set")
        self.open()
        self._pos_uint_or_str("_col", call.args)
        if not self.comma():
            raise self.error("expected ,")
        self.args_into(call.args)
        save = self.pos
        if self.comma():
            ts = self.timestamp_fmt()
            if ts is None:
                self.pos = save
            else:
                call.args["_timestamp"] = ts
        self.close()
        return call

    def _call_SetRowAttrs(self) -> Call:
        call = Call("SetRowAttrs")
        self.open()
        call.args["_field"] = self.field_name()
        if not self.comma():
            raise self.error("expected ,")
        self._pos_uint_or_str("_row", call.args)
        if not self.comma():
            raise self.error("expected ,")
        self.args_into(call.args)
        self.close()
        return call

    def _call_SetColumnAttrs(self) -> Call:
        call = Call("SetColumnAttrs")
        self.open()
        self._pos_uint_or_str("_col", call.args)
        if not self.comma():
            raise self.error("expected ,")
        self.args_into(call.args)
        self.close()
        return call

    def _call_Clear(self) -> Call:
        call = Call("Clear")
        self.open()
        self._pos_uint_or_str("_col", call.args)
        if not self.comma():
            raise self.error("expected ,")
        self.args_into(call.args)
        self.close()
        return call

    def _call_ClearRow(self) -> Call:
        call = Call("ClearRow")
        self.open()
        self.arg_into(call.args)
        self.close()
        return call

    def _call_Store(self) -> Call:
        call = Call("Store")
        self.open()
        call.children.append(self.call())
        if not self.comma():
            raise self.error("expected ,")
        self.arg_into(call.args)
        self.close()
        return call

    def _posfield_call(self, name: str) -> Call:
        call = Call(name)
        self.open()
        fe = self.match(_FIELD_RE)
        if fe is None:
            raise self.error("expected field name")
        call.args["_field"] = fe
        if self.comma():
            self._allargs_into(call)
        self.close()
        return call

    def _call_TopN(self) -> Call:
        return self._posfield_call("TopN")

    def _call_Rows(self) -> Call:
        return self._posfield_call("Rows")

    def _call_Range(self) -> Call:
        """Legacy time-range form: Range(f=10, [from=]ts, [to=]ts)
        (pql.peg Range rule); condition form falls back to generic."""
        call = Call("Range")
        self.open()
        field = self.field_name()
        self.sp()
        self.expect("=")
        self.sp()
        call.args[field] = self.value()
        if not self.comma():
            raise self.error("expected ,")
        self.literal("from=")
        ts = self.timestamp_fmt()
        if ts is None:
            raise self.error("expected timestamp")
        call.args["from"] = ts
        if not self.comma():
            raise self.error("expected ,")
        self.literal("to=")
        self.sp()
        ts = self.timestamp_fmt()
        if ts is None:
            raise self.error("expected timestamp")
        call.args["to"] = ts
        self.close()
        return call

    def _allargs_into(self, call: Call) -> None:
        while True:
            save = self.pos
            ident = self.match(_IDENT_RE)
            if ident is not None:
                self.sp()
                if self.peek() == "(":
                    self.pos = save
                    call.children.append(self.call())
                    if self.comma():
                        continue
                    return
            self.pos = save
            break
        save = self.pos
        self.sp()
        if self.peek() != ")":
            self.pos = save
            self.args_into(call.args)

    # ----------------------------------------------------------------- top

    def parse(self) -> Query:
        q = Query()
        self.sp()
        while self.pos < len(self.src):
            q.calls.append(self.call())
            self.sp()
        return q


def parse(src: str) -> Query:
    """Parse a PQL string into a Query (reference pql.ParseString).
    Both engines accept the full language including the executor's
    underscore sentinels; the PUBLIC-surface rejection of sentinel
    spellings is the single post-parse gate in pql.__init__
    (_reject_internal) so it cannot drift between engines."""
    if "\x00" in src:
        # NUL would truncate at the native parser's C-string boundary;
        # reject uniformly so both parsers accept the identical language
        raise ParseError("NUL byte in query", src, src.index("\x00"))
    return _Parser(src).parse()
