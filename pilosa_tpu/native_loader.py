"""Shared lazy build-and-load machinery for the C++ components.

One implementation of the g++-compile / ctypes-load / once-per-process
dance used by every native module (roaring codec, libpql), including
stale-binary recovery: if the on-disk .so fails to dlopen (foreign ABI,
torn write), it is rebuilt once from source and retried.  Build failures
latch — callers fall back to their Python implementations for the rest
of the process."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading


class NativeLib:
    """Lazily-built shared library.  `setup(lib)` declares the ctypes
    signatures after a successful load."""

    def __init__(self, src: str, so: str, setup,
                 extra_flags: tuple[str, ...] = ()):
        self.src = src
        self.so = so
        self.setup = setup
        self.extra_flags = tuple(extra_flags)
        self._lib = None
        self._failed = False
        self._lock = threading.Lock()

    def _build(self, force: bool = False) -> None:
        if (not force and os.path.exists(self.so)
                and os.path.getmtime(self.so) >= os.path.getmtime(self.src)):
            return
        os.makedirs(os.path.dirname(self.so), exist_ok=True)
        # per-process tmp name: concurrent cold builds must not publish
        # a torn .so
        tmp = f"{self.so}.tmp.{os.getpid()}"
        try:
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                 *self.extra_flags, "-o", tmp, self.src],
                check=True, capture_output=True)
            os.replace(tmp, self.so)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    def load(self):
        # double-checked: no lock on the hot path once loaded
        if self._lib is not None or self._failed:
            return self._lib
        with self._lock:
            if self._lib is not None or self._failed:
                return self._lib
            try:
                self._build()
                try:
                    lib = ctypes.CDLL(self.so)
                except OSError:
                    # stale or foreign-ABI binary: rebuild, retry once
                    self._build(force=True)
                    lib = ctypes.CDLL(self.so)
                self.setup(lib)
                self._lib = lib
            except Exception:
                self._failed = True
                self._lib = None
            return self._lib

    def available(self) -> bool:
        return self.load() is not None
