"""Hinted handoff: disk-backed per-peer queues of missed replica writes.

Before this module a single unreachable replica failed the whole write
(executor._replicate_to_shard_owners — the reference's all-owners
guarantee, executor.go:2137) and the only healing was the next
anti-entropy sweep.  With ``[replication] write-policy = "available"``
the write commits on the reachable owners and each missed delivery is
recorded as a HINT for the dead peer (the Dynamo/Cassandra hinted
handoff shape): a WAL-style append record in a per-peer file that
survives restart, bounded in bytes and age, replayed by a background
worker once the peer's circuit breaker closes or a heartbeat proves it
alive.  Anti-entropy (parallel/syncer.py) stays the backstop — a
dropped or expired hint only costs the cheaper repair path, never
correctness.

Record framing reuses the fragment WAL's roaring-record shape
(``models/fragment.py`` ``_WAL_ROARING_HDR``: one ``<BQQ`` header in
front of a length-prefixed blob), so replay tolerates a torn tail the
same way fragment replay does, and the append handle rides the same
``runtime/filebudget`` budgeted-fd machinery (flush-per-write like the
fragment WAL).

Process-wide configuration mirrors ``[mesh]``/``[containers]``:
``configure`` applies explicit values in place, the FIRST server to
``retain()`` captures the pre-server baseline and the LAST
``release()`` restores it (pilosa-lint P5).  The default policy is
``"all"`` — bare library embedders keep the reference's all-owners
write semantics byte-identical.
"""

from __future__ import annotations

import json
import os
import re
import struct
import threading
import time
from collections import deque
from dataclasses import dataclass

from pilosa_tpu import faultinject as _fi
from pilosa_tpu import lockcheck as _lockcheck
from pilosa_tpu import tracing as _tracing
from pilosa_tpu.runtime import filebudget

#: hint record framing — the fragment WAL's blob-record shape
#: (op byte, blob length, timestamp ms since epoch)
_HINT_HDR = struct.Struct("<BQQ")
_HINT_OP = 1

WRITE_POLICY_ALL = "all"
WRITE_POLICY_AVAILABLE = "available"


# --------------------------------------------------------------------
# process-wide [replication] runtime config
# --------------------------------------------------------------------


@dataclass
class ReplicationRuntimeConfig:
    """The [replication] knobs in force process-wide."""

    #: "all" fails the write when any owner delivery fails (the
    #: reference semantics, regression-pinned default); "available"
    #: commits on the reachable owners and hints the rest.
    write_policy: str = WRITE_POLICY_ALL
    #: total bytes of queued hints across all peers; 0 disables the
    #: hint queue entirely (missed deliveries count hint.dropped and
    #: anti-entropy alone heals them).
    hint_max_bytes: int = 16 << 20
    #: hints older than this are dropped at replay time (the peer was
    #: gone long enough that a full AE reconcile is the honest repair).
    hint_max_age: float = 3600.0
    #: replay worker scan period (seconds).
    replay_interval: float = 0.5


_cfg = ReplicationRuntimeConfig()
_cfg_lock = threading.Lock()
_baseline: tuple | None = None
_refs = 0


def config() -> ReplicationRuntimeConfig:
    return _cfg


def configure(write_policy: str | None = None,
              hint_max_bytes: int | None = None,
              hint_max_age: float | None = None,
              replay_interval: float | None = None) -> ReplicationRuntimeConfig:
    """Apply explicit values in place (None leaves a knob alone)."""
    if write_policy is not None and write_policy not in (
            WRITE_POLICY_ALL, WRITE_POLICY_AVAILABLE):
        raise ValueError(
            f"unknown write-policy {write_policy!r} (all|available)")
    with _cfg_lock:
        if write_policy is not None:
            _cfg.write_policy = write_policy
        if hint_max_bytes is not None:
            _cfg.hint_max_bytes = int(hint_max_bytes)
        if hint_max_age is not None:
            _cfg.hint_max_age = float(hint_max_age)
        if replay_interval is not None:
            _cfg.replay_interval = float(replay_interval)
    return _cfg


def retain() -> None:
    """First retain captures the pre-server baseline config."""
    global _refs, _baseline
    with _cfg_lock:
        if _refs == 0 and _baseline is None:
            _baseline = (_cfg.write_policy, _cfg.hint_max_bytes,
                         _cfg.hint_max_age, _cfg.replay_interval)
        _refs += 1


def release() -> None:
    """Last release restores the baseline for library users."""
    global _refs, _baseline
    with _cfg_lock:
        if _refs > 0:
            _refs -= 1
        if _refs == 0 and _baseline is not None:
            (_cfg.write_policy, _cfg.hint_max_bytes,
             _cfg.hint_max_age, _cfg.replay_interval) = _baseline
            _baseline = None


def reset() -> ReplicationRuntimeConfig:
    """Test hook: defaults, no baseline, zero refs."""
    global _cfg, _baseline, _refs
    with _cfg_lock:
        _cfg = ReplicationRuntimeConfig()
        _baseline = None
        _refs = 0
    return _cfg


# --------------------------------------------------------------------
# hint.* counters (published as gauges at scrape time, like tape.*)
# --------------------------------------------------------------------

_lock = _lockcheck.lock("hints-counters")
_counters = {
    "hint.queued": 0,          # hints appended to a peer queue
    "hint.replayed": 0,        # hints delivered to their peer
    "hint.dropped": 0,         # refused at append (disabled/overflow)
    "hint.expired": 0,         # aged out before delivery
    "hint.discarded": 0,       # dropped at replay (unowned refusal)
    "hint.replay_failures": 0, # replay attempts stopped by a dead peer
    "hint.torn_records": 0,    # torn tail records ignored at reload
}


def bump(name: str, value: int = 1) -> None:
    with _lock:
        _counters[name] += value


def counters() -> dict:
    with _lock:
        return dict(_counters)


def publish_gauges(stats, store: "HintStore | None" = None) -> None:
    """hint.* gauge family for /metrics and /debug/vars — published
    unconditionally (zeros on a clean server) so the family is
    alert-able before the first degraded write."""
    for name, v in counters().items():
        stats.gauge(name, v)
    depth = total_bytes = 0
    if store is not None:
        d = store.debug()
        depth = d["depth"]
        total_bytes = d["bytes"]
    stats.gauge("hint.depth", depth)
    stats.gauge("hint.bytes", total_bytes)


# --------------------------------------------------------------------
# store
# --------------------------------------------------------------------


class HintRecord:
    """One missed replica delivery: the single-shard PQL write that
    failed, replayable verbatim via transport.query_node.  The record
    blob carries the REAL peer id — filenames are sanitized, so the
    file name alone cannot round-trip arbitrary node names."""

    __slots__ = ("ts_ms", "peer", "index", "pql", "shard", "trace",
                 "raw")

    def __init__(self, ts_ms: int, peer: str, index: str, pql: str,
                 shard: int, raw: bytes, trace: str = ""):
        self.ts_ms = ts_ms
        self.peer = peer
        self.index = index
        self.pql = pql
        self.shard = shard
        # the write's trace id at queue time: replay re-attaches it so
        # the delivery RPC joins the original write's trace
        self.trace = trace
        self.raw = raw  # the exact appended bytes, for file rewrites

    @property
    def nbytes(self) -> int:
        return len(self.raw)

    @classmethod
    def make(cls, peer: str, index: str, pql: str, shard: int,
             ts_ms: int | None = None,
             trace: str = "") -> "HintRecord":
        ts = int(time.time() * 1e3) if ts_ms is None else ts_ms
        d = {"p": peer, "i": index, "q": pql, "s": shard}
        if trace:
            d["t"] = trace
        blob = json.dumps(d, separators=(",", ":")).encode()
        raw = _HINT_HDR.pack(_HINT_OP, len(blob), ts) + blob
        return cls(ts, peer, index, pql, shard, raw, trace=trace)


class _PeerQueue:
    __slots__ = ("records", "bytes", "wal", "draining")

    def __init__(self):
        self.records: deque[HintRecord] = deque()
        self.bytes = 0
        self.wal = None  # filebudget.BudgetedAppendFile | None
        self.draining = False


def _safe_name(peer_id: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]", "_", peer_id) or "_"


class HintStore:
    """Per-peer hint queues for ONE node, persisted under
    ``<data_dir>/hints/<peer>.hints`` (``dir_path=None`` = memory-only,
    for bare in-process test nodes without durability needs)."""

    def __init__(self, dir_path: str | None):
        self.dir = dir_path
        self._lock = _lockcheck.lock("hints")
        self._queues: dict[str, _PeerQueue] = {}
        self._total_bytes = 0
        if dir_path is not None:
            os.makedirs(dir_path, exist_ok=True)
            self._load()

    # ------------------------------------------------------------ load

    def _path(self, peer_id: str) -> str:
        # sanitized stem + short digest of the REAL id: two peers whose
        # names sanitize identically still get distinct files, and the
        # record blob (not the filename) is the identity of record
        import hashlib

        digest = hashlib.sha1(peer_id.encode()).hexdigest()[:8]
        return os.path.join(self.dir,
                            f"{_safe_name(peer_id)}-{digest}.hints")

    def _load(self) -> None:
        """Reload persisted queues.  Peer identity comes from the
        record blobs (filenames are sanitized and cannot round-trip
        arbitrary node names); every surviving queue is rewritten to
        its canonical file immediately, which also heals torn tails —
        appends through a plain append handle would otherwise land
        BEHIND torn bytes and vanish on the next reload (a dead peer
        never drains, so the drain-time rewrite cannot be the
        healer)."""
        torn = 0
        with self._lock:
            loaded: dict[str, list[HintRecord]] = {}
            seen: set[bytes] = set()
            sources: list[str] = []
            for name in sorted(os.listdir(self.dir)):
                if not name.endswith(".hints"):
                    continue
                path = os.path.join(self.dir, name)
                sources.append(path)
                recs, t = self._parse_file_locked(path)
                torn += t
                for rec in recs:
                    # dedup by exact record bytes: a crash between the
                    # canonical rewrite and the original's removal
                    # legitimately leaves both files on disk
                    if rec.raw in seen:
                        continue
                    seen.add(rec.raw)
                    loaded.setdefault(rec.peer, []).append(rec)
            # canonical rewrite FIRST (atomic via temp + replace),
            # originals removed only after every rewrite landed — a
            # crash anywhere in this window loses nothing
            canonical = set()
            for pid, rec_list in loaded.items():
                cpath = self._path(pid)
                tmp = cpath + ".tmp"
                with open(tmp, "wb") as f:
                    for rec in rec_list:
                        f.write(rec.raw)
                os.replace(tmp, cpath)
                canonical.add(cpath)
            for path in sources:
                if path not in canonical:
                    os.remove(path)
            for pid, rec_list in loaded.items():
                q = _PeerQueue()
                q.records.extend(rec_list)
                q.bytes = sum(r.nbytes for r in rec_list)
                q.wal = filebudget.open_append(self._path(pid))
                self._queues[pid] = q
                self._total_bytes += q.bytes
        if torn:
            bump("hint.torn_records", torn)

    def _parse_file_locked(
            self, path: str) -> tuple[list[HintRecord], int]:
        """Parse one persisted file; returns (records, torn) — torn is
        0 or 1 (parsing stops at the first tear, exactly like fragment
        WAL replay)."""
        with open(path, "rb") as f:
            buf = f.read()
        out: list[HintRecord] = []
        off, n = 0, len(buf)
        while off + _HINT_HDR.size <= n:
            op, blob_len, ts_ms = _HINT_HDR.unpack_from(buf, off)
            if op != _HINT_OP or off + _HINT_HDR.size + blob_len > n:
                return out, 1  # torn/corrupt tail: ignore, WAL-style
            start = off
            off += _HINT_HDR.size
            blob = buf[off:off + blob_len]
            off += blob_len
            try:
                d = json.loads(blob)
                rec = HintRecord(ts_ms, str(d["p"]), d["i"], d["q"],
                                 int(d["s"]), bytes(buf[start:off]),
                                 trace=str(d.get("t", "")))
            except Exception:  # noqa: BLE001 — corrupt blob: stop
                return out, 1
            out.append(rec)
        return out, 1 if off != n else 0

    # ---------------------------------------------------------- append

    def append(self, peer_id: str, index: str, pql: str,
               shard: int) -> bool:
        """Queue one missed delivery for ``peer_id``.  Returns False
        (and counts ``hint.dropped``) when the queue is disabled or the
        byte bound would be exceeded — the caller's write still
        commits; anti-entropy repairs the peer."""
        cfg = config()
        if cfg.hint_max_bytes <= 0:
            bump("hint.dropped")
            return False
        rec = HintRecord.make(peer_id, index, pql, shard,
                              trace=_tracing.active_trace_id() or "")
        with self._lock:
            if self._total_bytes + rec.nbytes > cfg.hint_max_bytes:
                over = True
            else:
                over = False
                q = self._queue_locked(peer_id)
                q.records.append(rec)
                q.bytes += rec.nbytes
                self._total_bytes += rec.nbytes
                if q.wal is not None:
                    q.wal.write(rec.raw)
        bump("hint.dropped" if over else "hint.queued")
        return not over

    def _queue_locked(self, peer_id: str) -> _PeerQueue:
        q = self._queues.get(peer_id)
        if q is None:
            q = self._queues[peer_id] = _PeerQueue()
            if self.dir is not None:
                q.wal = filebudget.open_append(self._path(peer_id))
        return q

    # ---------------------------------------------------------- replay

    def replay_peer(self, peer_id: str, deliver) -> dict:
        """Drain ``peer_id``'s queue in order through ``deliver(rec)``.
        Delivery raising an unowned-shard refusal discards the hint
        (ownership moved; anti-entropy owns the repair); any other
        exception stops the drain (the peer is still unhealthy) and the
        remaining hints wait for the next attempt.  Returns
        ``{"replayed", "expired", "discarded", "failed", "error"}``.

        The store lock is NEVER held across a delivery RPC: the head
        of the queue is snapshotted, delivered outside the lock, and
        the consumed prefix removed afterward (concurrent appends land
        behind the snapshot and survive untouched)."""
        from pilosa_tpu.parallel.cluster import refusal_is_unowned

        out = {"replayed": 0, "expired": 0, "discarded": 0,
               "failed": False, "error": None}
        with self._lock:
            q = self._queues.get(peer_id)
            if q is None or q.draining or not q.records:
                return out
            q.draining = True
            batch = list(q.records)
        max_age = config().hint_max_age
        now_ms = time.time() * 1e3
        consumed = 0
        try:
            for rec in batch:
                if max_age > 0 and now_ms - rec.ts_ms > max_age * 1e3:
                    out["expired"] += 1
                    consumed += 1
                    continue
                try:
                    # re-attach the queued write's trace (or mint one
                    # for pre-trace records) so the replay RPC carries
                    # traceparent and joins the original write's trace
                    with _tracing.propagate(rec.trace
                                            or _tracing.new_trace_id()):
                        deliver(rec)
                except Exception as e:  # noqa: BLE001 — classified below
                    if refusal_is_unowned(e):
                        out["discarded"] += 1
                        consumed += 1
                        continue
                    out["failed"] = True
                    out["error"] = e
                    break
                out["replayed"] += 1
                consumed += 1
        finally:
            with self._lock:
                for _ in range(consumed):
                    r = q.records.popleft()
                    q.bytes -= r.nbytes
                    self._total_bytes -= r.nbytes
                if consumed:
                    self._rewrite_locked(peer_id, q)
                q.draining = False
        if out["replayed"]:
            bump("hint.replayed", out["replayed"])
        if out["expired"]:
            bump("hint.expired", out["expired"])
        if out["discarded"]:
            bump("hint.discarded", out["discarded"])
        if out["failed"]:
            bump("hint.replay_failures")
        return out

    def _rewrite_locked(self, peer_id: str, q: _PeerQueue) -> None:
        """Persist the post-drain remainder atomically (temp +
        os.replace, the same hardening _load has): a truncate-in-place
        rewrite killed mid-way would lose every undrained hint.  The
        file is small by construction (hint_max_bytes bound)."""
        if q.wal is None:
            return
        q.wal.close()
        cpath = self._path(peer_id)
        tmp = cpath + ".tmp"
        with open(tmp, "wb") as f:
            for rec in q.records:
                f.write(rec.raw)
            f.flush()
        os.replace(tmp, cpath)
        q.wal = filebudget.open_append(cpath)

    # ----------------------------------------------------------- views

    def peers(self) -> list[str]:
        with self._lock:
            return sorted(p for p, q in self._queues.items()
                          if q.records)

    def depth(self, peer_id: str) -> int:
        with self._lock:
            q = self._queues.get(peer_id)
            return 0 if q is None else len(q.records)

    def total_depth(self) -> int:
        with self._lock:
            return sum(len(q.records) for q in self._queues.values())

    def debug(self) -> dict:
        """The per-peer section of /debug/antientropy."""
        now_ms = time.time() * 1e3
        with self._lock:
            peers = {}
            depth = 0
            for pid, q in sorted(self._queues.items()):
                if not q.records:
                    continue
                depth += len(q.records)
                oldest = q.records[0].ts_ms
                peers[pid] = {
                    "depth": len(q.records),
                    "bytes": q.bytes,
                    "oldestAgeS": round(max(0.0,
                                            (now_ms - oldest) / 1e3), 3),
                }
            return {"depth": depth, "bytes": self._total_bytes,
                    "peers": peers}

    def close(self) -> None:
        with self._lock:
            for q in self._queues.values():
                if q.wal is not None:
                    q.wal.close()
                    q.wal = None


# --------------------------------------------------------------------
# replay worker
# --------------------------------------------------------------------


class HintReplayer:
    """Background drain loop for one node's hint store.

    Every ``[replication] replay-interval`` seconds each peer with
    queued hints is considered: a peer whose circuit breaker is open
    (still cooling down) is skipped without an RPC — the breaker
    closing (via real traffic or a successful SWIM heartbeat probe,
    Cluster.note_probe) is exactly the "peer came back" signal that
    lets the next scan drain it.  A failed drain attempt backs the
    peer off exponentially (capped) so a flapping peer is not hammered
    with its whole backlog every scan."""

    BACKOFF_CAP_S = 30.0

    def __init__(self, node, interval_s: float | None = None):
        self.node = node
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # peer -> (monotonic not-before, current delay); only touched
        # by the replay thread / run_once callers (externally
        # serialized — the store's per-peer draining flag makes a
        # concurrent run_once a no-op for in-flight peers anyway)
        self._backoff: dict[str, tuple[float, float]] = {}

    def _interval(self) -> float:
        if self.interval_s is not None:
            return self.interval_s
        return max(0.05, config().replay_interval)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        t = threading.Thread(target=self._loop, daemon=True,
                             name="hint-replay")
        self._thread = t
        t.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self._interval()):
            try:
                self.run_once()
            except Exception:  # noqa: BLE001 — the drain loop must
                # survive any single peer's weirdness; the next scan
                # retries
                pass

    def run_once(self, force: bool = False) -> dict:
        """One scan over every peer with queued hints.  ``force``
        ignores breaker state and backoff (tests / operator kicks).
        Returns aggregate counts."""
        from pilosa_tpu.serve.admission import rpc_class

        store = getattr(self.node, "hints", None)
        cluster = self.node.cluster
        totals = {"replayed": 0, "expired": 0, "discarded": 0,
                  "failed_peers": 0, "skipped_peers": 0}
        if store is None or cluster.transport is None:
            return totals
        now = time.monotonic()
        for pid in store.peers():
            peer = cluster.node(pid)
            if peer is None:
                # the peer left the cluster: its hints can never land
                store.replay_peer(pid, self._drop_all)
                continue
            if not force:
                nb, _ = self._backoff.get(pid, (0.0, 0.0))
                if now < nb or cluster.breaker_open(pid):
                    totals["skipped_peers"] += 1
                    continue
            with rpc_class("internal"):
                res = store.replay_peer(pid, self._deliver_fn(peer))
            for k in ("replayed", "expired", "discarded"):
                totals[k] += res[k]
            if res["failed"]:
                totals["failed_peers"] += 1
                self._note_failure(pid, res["error"])
            else:
                self._backoff.pop(pid, None)
                if res["replayed"]:
                    cluster.note_peer_success(pid)
        return totals

    @staticmethod
    def _drop_all(rec) -> None:
        from pilosa_tpu.parallel.cluster import UNOWNED_MARKER

        raise RuntimeError(f"{UNOWNED_MARKER}: peer removed")

    def _deliver_fn(self, peer):
        transport = self.node.cluster.transport

        def deliver(rec: HintRecord) -> None:
            if _fi.armed:
                # failpoint: the production hint replay delivery
                # (errors here leave the hint queued for the next scan)
                _fi.hit("hint.replay")
            transport.query_node(peer, rec.index, rec.pql, [rec.shard])

        return deliver

    def _note_failure(self, pid: str, error) -> None:
        from pilosa_tpu.parallel.cluster import ShedByPeerError

        cluster = self.node.cluster
        if isinstance(error, ShedByPeerError):
            # proof of life: the peer is up but loaded — back off
            # without feeding its breaker
            cluster.note_peer_success(pid)
        else:
            cluster.note_peer_failure(pid)
        _, prev = self._backoff.get(pid, (0.0, 0.0))
        delay = min(self.BACKOFF_CAP_S,
                    max(self._interval(), 0.1) if prev <= 0 else prev * 2)
        self._backoff[pid] = (time.monotonic() + delay, delay)
