"""Live rebalance: online shard migration under traffic.

The legacy resize protocol (parallel/resize.py, the reference's
cluster.go:1196-1561) is stop-the-world: the whole cluster goes
RESIZING and 405s every read and write for the duration.  This module
is the online replacement — node add/remove as a first-class operation
that keeps serving.  A coordinator computes the ownership diff per
shard (reusing ``resize.plan_transfers``) and drives each
``(index, shard)`` through an explicit per-shard state machine::

    source-serving -> dual-write -> backfill -> cutover -> dropped

instead of one cluster-wide gate:

- **dual-write** — a routing OVERRIDE is installed on every node
  (``Cluster.set_shard_route``): reads keep resolving to the still-
  authoritative old owners, while writes commit on old AND new owners
  (``Cluster.write_nodes``; a missed delivery to a new owner falls
  back to the hinted-handoff queue, parallel/hints.py, with
  anti-entropy as the backstop).
- **backfill** — the destination pulls the fragment via the
  anti-entropy digest/block machinery (checksum exchange, block-data
  pulls, positional import) under the admission **internal** class,
  bounded by a concurrent-transfer budget.  A transfer target whose
  circuit breaker is open pauses THAT shard's backfill with
  exponential backoff — the rest of the plan keeps moving, and
  breakers + hedged reads steer queries around the slow peer.
- **cutover** — one broadcast atomically flips routing for that shard
  only (serving=new, pending=old: writes stay dual until commit so an
  abort can always fall back to the old owners without losing
  writes), invalidates the affected result-cache entries everywhere,
  and drops the losing node's device stacks — residency placements
  and tenant byte-attribution move with them.
- **dropped** — at commit the membership change is finalized, the
  overrides are cleared (ring math now equals them), and the old
  copies age out through the grace-deferred holder cleanup.

The plan and every per-shard state transition persist to a JSON cursor
(``<data-dir>/.rebalance``, tmp+rename): a coordinator crash or an
operator ``abort`` leaves the cluster serving on the old topology,
never half-gated, and a restarted coordinator resumes mid-plan from
the cursor.  Readers never 405 — a shard mid-migration serves from
the still-authoritative owner.

Process-wide configuration follows the [replication] shape
(pilosa-lint P5): ``configure`` applies explicit values in place, the
FIRST server to ``retain()`` captures the pre-server baseline and the
LAST ``release()`` restores it.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass

from pilosa_tpu import lockcheck as _lockcheck
from pilosa_tpu import observe as _observe
from pilosa_tpu import tracing as _tracing
from pilosa_tpu.parallel.cluster import (
    Node,
    ShedByPeerError,
    TransportError,
)
from pilosa_tpu.parallel.resize import plan_transfers
from pilosa_tpu.serve import deadline as _deadline
from pilosa_tpu.serve.admission import tagged
from pilosa_tpu.shardwidth import SHARD_WIDTH

#: per-shard migration states (the ISSUE's state machine; "source-
#: serving" is the implicit state before the begin broadcast installs
#: the dual-write override)
MOVE_DUAL_WRITE = "dual-write"
MOVE_BACKFILL = "backfill"
MOVE_CUTOVER = "cutover"
MOVE_DROPPED = "dropped"

DUAL_WRITE_HINT = "hint"
DUAL_WRITE_STRICT = "strict"

#: cursor file name under the coordinator's data dir
CURSOR_FILENAME = ".rebalance"


class RebalanceError(RuntimeError):
    pass


# --------------------------------------------------------------------
# process-wide [rebalance] runtime config (pilosa-lint P5)
# --------------------------------------------------------------------


@dataclass
class RebalanceRuntimeConfig:
    """The [rebalance] knobs in force process-wide."""

    #: max concurrent shard transfers the coordinator drives
    transfer_budget: int = 2
    #: "hint" never fails a write on a missed delivery to a PENDING
    #: (not-yet-cut-over) owner — the hint queue + anti-entropy
    #: converge it; "strict" holds pending owners to the same
    #: [replication] write-policy as serving owners.
    dual_write_policy: str = DUAL_WRITE_HINT
    #: persisted plan cursor path ("" = <data-dir>/.rebalance)
    cursor_path: str = ""
    #: exponential-backoff base for a paused/failed shard transfer
    backoff_base: float = 0.2
    #: backoff cap (matches the hint replayer's ceiling)
    backoff_cap: float = 30.0
    #: per-block-exchange deadline on the backfill pull path
    peer_timeout: float = 2.0


_cfg = RebalanceRuntimeConfig()
_cfg_lock = threading.Lock()
_baseline: tuple | None = None
_refs = 0


def config() -> RebalanceRuntimeConfig:
    return _cfg


def configure(transfer_budget: int | None = None,
              dual_write_policy: str | None = None,
              cursor_path: str | None = None,
              backoff_base: float | None = None,
              backoff_cap: float | None = None,
              peer_timeout: float | None = None) -> RebalanceRuntimeConfig:
    """Apply explicit values in place (None leaves a knob alone)."""
    if dual_write_policy is not None and dual_write_policy not in (
            DUAL_WRITE_HINT, DUAL_WRITE_STRICT):
        raise ValueError(
            f"unknown dual-write-policy {dual_write_policy!r} "
            f"(hint|strict)")
    with _cfg_lock:
        if transfer_budget is not None:
            _cfg.transfer_budget = max(1, int(transfer_budget))
        if dual_write_policy is not None:
            _cfg.dual_write_policy = dual_write_policy
        if cursor_path is not None:
            _cfg.cursor_path = cursor_path
        if backoff_base is not None:
            _cfg.backoff_base = float(backoff_base)
        if backoff_cap is not None:
            _cfg.backoff_cap = float(backoff_cap)
        if peer_timeout is not None:
            _cfg.peer_timeout = float(peer_timeout)
    return _cfg


def retain() -> None:
    """First retain captures the pre-server baseline config."""
    global _refs, _baseline
    with _cfg_lock:
        if _refs == 0 and _baseline is None:
            _baseline = (_cfg.transfer_budget, _cfg.dual_write_policy,
                         _cfg.cursor_path, _cfg.backoff_base,
                         _cfg.backoff_cap, _cfg.peer_timeout)
        _refs += 1


def release() -> None:
    """Last release restores the baseline for library users."""
    global _refs, _baseline
    with _cfg_lock:
        if _refs > 0:
            _refs -= 1
        if _refs == 0 and _baseline is not None:
            (_cfg.transfer_budget, _cfg.dual_write_policy,
             _cfg.cursor_path, _cfg.backoff_base,
             _cfg.backoff_cap, _cfg.peer_timeout) = _baseline
            _baseline = None


def reset() -> RebalanceRuntimeConfig:
    """Test hook: defaults, no baseline, zero refs."""
    global _cfg, _baseline, _refs
    with _cfg_lock:
        _cfg = RebalanceRuntimeConfig()
        _baseline = None
        _refs = 0
    return _cfg


# --------------------------------------------------------------------
# rebalance.* counters (published as gauges at scrape time, like ae.*)
# --------------------------------------------------------------------

_lock = _lockcheck.lock("rebalance-counters")
_counters = {
    "rebalance.plans": 0,             # rebalance plans started
    "rebalance.cutovers": 0,          # shards cut over to new owners
    "rebalance.bytes_streamed": 0,    # backfill payload bytes applied
    "rebalance.dual_writes": 0,       # write deliveries to pending owners
    "rebalance.aborts": 0,            # plans aborted back to old topology
    "rebalance.resumes": 0,           # plans resumed from the cursor
    "rebalance.backoffs": 0,          # transfers paused on an open breaker
    "rebalance.transfer_failures": 0, # failed transfer attempts (retried)
}


def bump(name: str, value: int = 1) -> None:
    with _lock:
        _counters[name] += value


def counters() -> dict:
    with _lock:
        return dict(_counters)


def publish_gauges(stats, driver: "RebalanceCoordinator | None" = None
                   ) -> None:
    """rebalance.* gauge family for /metrics and /debug/vars —
    published unconditionally (zeros on a clean server) so the family
    is alert-able before the first migration."""
    for name, v in counters().items():
        stats.gauge(name, v)
    pending = moving = cutover = 0
    if driver is not None:
        pending, moving, cutover = driver.shard_state_counts()
    stats.gauge("rebalance.shards_pending", pending)
    stats.gauge("rebalance.shards_moving", moving)
    stats.gauge("rebalance.shards_cutover", cutover)


# --------------------------------------------------------------------
# destination-side backfill (the AE digest/block pull, one direction)
# --------------------------------------------------------------------


def _exchange(cluster, n: Node, message: dict, timeout: float) -> dict:
    """One deadline-bounded peer RPC with breaker feedback — the
    FragmentSyncer._exchange contract (a shed reply is proof of life,
    a transport error feeds the peer's breaker)."""
    try:
        with _deadline.scope(_deadline.Deadline(timeout)):
            resp = cluster.transport.send_message(n, message)
    except ShedByPeerError:
        cluster.note_peer_success(n.id)
        raise
    except (TransportError, _deadline.DeadlineExceededError,
            TimeoutError, OSError):
        cluster.note_peer_failure(n.id)
        raise
    cluster.note_peer_success(n.id)
    return resp


def _pull_view(node, src: Node, index: str, field: str, view: str,
               shard: int, timeout: float) -> int:
    """Pull one view of one fragment from `src` into the local holder
    via the anti-entropy block machinery: exchange checksums, pull
    only the differing blocks' positions, import.  Dual-written bits
    already present locally cost nothing.  Returns payload bytes
    applied (8 bytes per pulled position)."""
    resp = _exchange(node.cluster, src, {
        "type": "fragment-blocks",
        "index": index, "field": field, "view": view, "shard": shard,
    }, timeout)
    src_blocks = {b["id"]: b["checksum"] for b in resp.get("blocks", [])}
    if not src_blocks:
        return 0
    frag = node.local_fragment(index, field, view, shard, True)
    local_blocks = {}
    if frag is not None:
        blocks, _hit = frag.blocks_with_flag()
        local_blocks = {b["id"]: b["checksum"] for b in blocks}
    dirty = [bid for bid, ck in src_blocks.items()
             if local_blocks.get(bid) != ck]
    total = 0
    for bid in sorted(dirty):
        data = _exchange(node.cluster, src, {
            "type": "fragment-block-data",
            "index": index, "field": field, "view": view,
            "shard": shard, "block": bid,
        }, timeout)
        pairs = list(zip(data.get("rowIDs", []),
                         data.get("columnIDs", [])))
        if pairs:
            frag.import_positions(
                [r * SHARD_WIDTH + c for r, c in pairs])
            total += 8 * len(pairs)
    return total


def _pull_field(node, src: Node, index: str, field: str,
                shard: int, timeout: float) -> int:
    """Pull every view of one (index, field, shard) from `src`.
    Raises TransportError when the source holds no data — like the
    offline path's _fetch_fragment, so the caller falls back to
    another replica instead of recording an empty transfer as done."""
    resp = _exchange(node.cluster, src, {
        "type": "fragment-views",
        "index": index, "field": field, "shard": shard,
    }, timeout)
    views = resp.get("views") or []
    if not views:
        raise TransportError(
            f"source {src.id} has no data for {index}/{field}/shard "
            f"{shard}")
    idx = node.holder.index(index)
    f = None if idx is None else idx.field(field)
    if f is None:
        raise RebalanceError(f"field not found locally: {field}")
    total = 0
    for vname in views:
        f.create_view_if_not_exists(vname).create_fragment_if_not_exists(
            shard)
        total += _pull_view(node, src, index, field, vname, shard,
                            timeout)
    f._note_shard(shard)
    return total


@tagged("internal")
def follow_transfer(node, msg: dict) -> dict:
    """Destination-side ``rebalance-transfer``: pull every assigned
    field of one shard from its source (fallbacks on failure), ack
    with the payload byte count.  Rides the internal admission class
    end to end so a backfill can never starve user queries."""
    index = msg["index"]
    shard = int(msg["shard"])
    uris = msg.get("uris", {})
    timeout = config().peer_timeout
    total = 0
    for t in msg.get("fields", []):
        sources = [t["source"]] + list(t.get("fallbacks", []))
        last_err = None
        done = False
        empty = 0
        for src_id in sources:
            src = node.cluster.node(src_id) or Node(
                id=src_id, uri=uris.get(src_id, ""))
            if src.uri == "" and src_id in uris:
                src.uri = uris[src_id]
            try:
                total += _pull_field(node, src, index, t["field"],
                                     shard, timeout)
                done = True
                break
            except (TransportError, _deadline.DeadlineExceededError,
                    TimeoutError, OSError) as e:
                last_err = e
                if "has no data for" in str(e):
                    empty += 1
        if not done and empty == len(sources):
            # every replica is genuinely empty for this field/shard:
            # nothing to move (dual-writes and AE cover anything new)
            continue
        if not done:
            return {"ok": False,
                    "error": f"no reachable source for {index}/"
                             f"{t['field']}/shard {shard}: {last_err}"}
    return {"ok": True, "bytes": total}


# --------------------------------------------------------------------
# node-side broadcast handlers
# --------------------------------------------------------------------


def apply_begin(node, msg: dict) -> dict:
    """``rebalance-begin``: adopt the (possibly extended) membership
    and schema, then install the dual-write routing overrides.  The
    joining node receives this as its first cluster contact — it is
    probe-able and breaker-tracked from here on, before it owns
    anything."""
    node.holder.apply_schema(msg.get("schema", []))
    status = msg.get("status")
    if status:
        if node.cluster.apply_status(status):
            node._broadcast_self_alive()
    for r in msg.get("routes", []):
        node.cluster.set_shard_route(r["index"], int(r["shard"]),
                                     r.get("serving", ()),
                                     r.get("pending", ()))
    return {"ok": True}


def apply_cutover(node, msg: dict) -> dict:
    """``rebalance-cutover``: flip routing for ONE shard (serving=new,
    pending=old — writes stay dual until commit so abort can always
    fall back), invalidate the shard's result-cache entries, and on a
    node losing ownership drop its device stacks so residency
    placements and tenant byte-attribution move with the shard."""
    index = msg["index"]
    shard = int(msg["shard"])
    serving = list(msg.get("serving", ()))
    pending = list(msg.get("pending", ()))
    node.cluster.set_shard_route(index, shard, serving, pending)
    _invalidate_shard_local(node, index, shard,
                            losing=node.cluster.local_id not in serving)
    return {"ok": True}


def apply_abort(node, msg: dict) -> dict:
    """``rebalance-abort``: clear every routing override (ring math
    over the OLD membership takes back over) and forget the node that
    was joining — the cluster serves exactly the old topology."""
    routed = node.cluster.clear_shard_routes()
    add_id = msg.get("add_id")
    if add_id and add_id != node.cluster.local_id:
        node.cluster.remove_node(add_id)
    for index, shard in routed:
        _invalidate_shard_local(node, index, shard, losing=False)
    return {"ok": True}


def apply_commit(node, msg: dict) -> dict:
    """``rebalance-commit``: adopt the final membership and drop every
    override — placement math over the new member set now equals the
    cut-over routes, so routing does not move."""
    status = msg.get("status")
    if status:
        if node.cluster.apply_status(status):
            node._broadcast_self_alive()
    node.cluster.clear_shard_routes()
    return {"ok": True}


def _invalidate_shard_local(node, index: str, shard: int,
                            losing: bool) -> None:
    """Cutover-time local invalidation: the shard's result-cache
    entries everywhere (a stale remote-map entry on an ex-owner would
    otherwise serve frozen results forever — its generation stamps
    stop moving once writes stop arriving), plus the losing node's
    per-shard device stacks (residency forget reverses the tenant
    byte charges, so attribution moves with the data)."""
    from pilosa_tpu.runtime import resultcache

    resultcache.cache().invalidate_shard(index, shard)
    if not losing:
        return
    idx = node.holder.index(index)
    if idx is None:
        return
    for f in idx.all_fields():
        f.drop_shard_stacks(shard)


# --------------------------------------------------------------------
# coordinator
# --------------------------------------------------------------------


class RebalanceCoordinator:
    """Coordinator-side online rebalance driver.

    One plan at a time: ``start`` computes the ownership diff, installs
    dual-write overrides cluster-wide, then a bounded worker pool
    drives each shard through backfill -> cutover; ``commit`` finalizes
    membership.  The plan persists to a JSON cursor after every state
    transition — ``resume()`` (called from Server.open) picks a crashed
    plan back up; ``abort()`` reverts to the old topology.
    """

    def __init__(self, node, cursor_path: str | None = None):
        self.node = node
        self.cluster = node.cluster
        self._explicit_cursor = cursor_path
        self._plan_lock = _lockcheck.lock("rebalance-driver")
        self._plan: dict | None = None
        self._thread: threading.Thread | None = None
        self._halt = threading.Event()
        self._abort_requested = False
        self._last: dict | None = None
        # serializes cursor writes: concurrent workers persist after
        # every state transition, and the tmp+rename pair is not safe
        # to interleave (the loser's os.replace finds no tmp file)
        self._persist_lock = threading.Lock()

    # ------------------------------------------------------------ paths

    @property
    def cursor_path(self) -> str:
        if self._explicit_cursor:
            return self._explicit_cursor
        cfg_path = config().cursor_path
        if cfg_path:
            return cfg_path
        return os.path.join(str(self.node.holder.path), CURSOR_FILENAME)

    # ----------------------------------------------------------- status

    def active(self) -> bool:
        with self._plan_lock:
            return self._plan is not None

    def shard_state_counts(self) -> tuple[int, int, int]:
        """(pending, moving, cutover) shard counts of the active plan
        — the rebalance.shards_* gauges."""
        with self._plan_lock:
            plan = self._plan
            if plan is None:
                return (0, 0, 0)
            pending = moving = cut = 0
            for m in plan["shards"]:
                if m["state"] == MOVE_DUAL_WRITE:
                    pending += 1
                elif m["state"] == MOVE_BACKFILL:
                    moving += 1
                elif m["state"] in (MOVE_CUTOVER, MOVE_DROPPED):
                    cut += 1
            return (pending, moving, cut)

    def status(self) -> dict:
        """The /debug/rebalance document."""
        with self._plan_lock:
            plan = self._plan
            doc: dict = {
                "active": plan is not None,
                "counters": counters(),
                "cursorPath": self.cursor_path,
            }
            if plan is not None:
                doc["plan"] = {
                    "add": plan.get("add"),
                    "removeId": plan.get("remove_id"),
                    "startedAt": plan.get("started_at"),
                    "shards": [
                        {"index": m["index"], "shard": m["shard"],
                         "state": m["state"],
                         "old": m["old"], "new": m["new"]}
                        for m in plan["shards"]
                    ],
                }
            if self._last is not None:
                doc["last"] = self._last
        pending, moving, cut = self.shard_state_counts()
        doc["shardsPending"] = pending
        doc["shardsMoving"] = moving
        doc["shardsCutover"] = cut
        return doc

    # ------------------------------------------------------------ start

    def start(self, add: Node | None = None,
              remove_id: str | None = None,
              background: bool = True) -> dict:
        """Begin an online rebalance.  Returns the plan summary
        immediately; transfers run on a background worker pool unless
        ``background=False`` (tests)."""
        c = self.cluster
        if not c.is_coordinator:
            raise RebalanceError("rebalance must run on the coordinator")
        if remove_id == c.local_id:
            raise RebalanceError(
                "cannot remove the coordinator: move the role first "
                "(POST /cluster/resize/set-coordinator)")
        with self._plan_lock:
            if self._plan is not None:
                raise RebalanceError("a rebalance is already running")
            from pilosa_tpu.parallel.cluster import STATE_RESIZING

            if c.state == STATE_RESIZING:
                raise RebalanceError("an offline resize is running")
            old_ids = [n.id for n in c.sorted_nodes()]
            new_ids = list(old_ids)
            if add is not None and add.id not in new_ids:
                new_ids.append(add.id)
            if remove_id is not None:
                if remove_id not in new_ids:
                    raise RebalanceError(f"node not found: {remove_id}")
                new_ids.remove(remove_id)
            if sorted(new_ids) == sorted(old_ids):
                return {"started": False, "shards": 0,
                        "nodes": sorted(new_ids)}
            plan = self._build_plan(add, remove_id, old_ids, new_ids)
            self._plan = plan
            self._abort_requested = False
            self._halt.clear()
        bump("rebalance.plans")
        if _observe.journal_on:
            _observe.emit("rebalance.plan", trace_id=plan["trace"],
                          shards=len(plan["shards"]),
                          nodes=sorted(new_ids))
        with _tracing.propagate(plan["trace"]):
            self._persist()
            self._broadcast_begin(plan)
        summary = {"started": True, "shards": len(plan["shards"]),
                   "nodes": sorted(new_ids),
                   "add": plan.get("add"),
                   "removeId": plan.get("remove_id")}
        if background:
            self._spawn()
        else:
            self._run()
        return summary

    def _build_plan(self, add: Node | None, remove_id: str | None,
                    old_ids: list[str], new_ids: list[str]) -> dict:
        c = self.cluster
        raw = plan_transfers(self.node.holder, old_ids, new_ids,
                             c.replica_n, c.partition_n, c.hasher)
        from pilosa_tpu.parallel.cluster import shard_owners

        moves: dict[tuple[str, int], dict] = {}
        for dest_id, transfers in raw.items():
            for t in transfers:
                key = (t["index"], t["shard"])
                m = moves.get(key)
                if m is None:
                    m = moves[key] = {
                        "index": t["index"], "shard": t["shard"],
                        "old": shard_owners(sorted(old_ids), t["index"],
                                            t["shard"], c.replica_n,
                                            c.partition_n, c.hasher),
                        "new": shard_owners(sorted(new_ids), t["index"],
                                            t["shard"], c.replica_n,
                                            c.partition_n, c.hasher),
                        "state": MOVE_DUAL_WRITE,
                        "dests": {},
                    }
                m["dests"].setdefault(dest_id, []).append(
                    {"field": t["field"], "source": t["source"],
                     "fallbacks": t.get("fallbacks", [])})
        ordered = sorted(moves.values(),
                         key=lambda m: (m["index"], m["shard"]))
        return {
            "add": add.to_dict() if add is not None else None,
            "remove_id": remove_id,
            "old_ids": sorted(old_ids),
            "new_ids": sorted(new_ids),
            "shards": ordered,
            "started_at": time.time(),
            "done": False,
            # one trace id for the plan's lifetime: every backfill
            # transfer, cutover broadcast, and journal event this plan
            # produces (on any worker thread, across resume) joins it
            "trace": _tracing.new_trace_id(),
        }

    # ------------------------------------------------------ persistence

    def _persist(self) -> None:
        """Write the plan cursor atomically (tmp+rename, the topology
        file discipline) so every state transition survives a crash."""
        with self._plan_lock:
            plan = self._plan
            if plan is None:
                return
            data = json.dumps(plan)
        path = self.cursor_path
        tmp = path + ".tmp"
        with self._persist_lock:
            with open(tmp, "w") as f:
                f.write(data)
            os.replace(tmp, path)

    def _clear_cursor(self) -> None:
        try:
            os.remove(self.cursor_path)
        except FileNotFoundError:
            pass

    def resume(self) -> bool:
        """Pick an interrupted plan back up from the persisted cursor
        (Server.open on the coordinator).  Re-broadcasts membership
        and routes (idempotent on nodes that never lost them), then
        continues transfers for shards not yet cut over."""
        path = self.cursor_path
        if not os.path.exists(path):
            return False
        try:
            with open(path) as f:
                plan = json.load(f)
        except (OSError, ValueError):
            return False
        if plan.get("done"):
            self._clear_cursor()
            return False
        with self._plan_lock:
            if self._plan is not None:
                return False
            self._plan = plan
            self._abort_requested = False
            self._halt.clear()
        bump("rebalance.resumes")
        self._broadcast_begin(plan)
        # shards already cut over flipped their routes in
        # _broadcast_begin (route derivation is state-aware); resume
        # the rest
        self._spawn()
        return True

    # -------------------------------------------------------- broadcast

    def _route_for(self, m: dict) -> dict:
        if m["state"] in (MOVE_CUTOVER, MOVE_DROPPED):
            serving = m["new"]
            pending = [i for i in m["old"] if i not in m["new"]]
        else:
            serving = m["old"]
            pending = [i for i in m["new"] if i not in m["old"]]
        return {"index": m["index"], "shard": m["shard"],
                "serving": serving, "pending": pending}

    def _broadcast_begin(self, plan: dict) -> None:
        c = self.cluster
        add = plan.get("add")
        if add is not None:
            c.add_node(Node.from_dict(add))
        status = c.to_status()
        msg = {
            "type": "rebalance-begin",
            "schema": self.node.holder.schema(),
            "status": status,
            "routes": [self._route_for(m) for m in plan["shards"]],
        }
        self.node.receive_message(msg)
        self.node.broadcast(msg)

    def _send(self, node_id: str, msg: dict) -> dict:
        if node_id == self.cluster.local_id:
            return self.node.receive_message(msg)
        dest = self.cluster.node(node_id)
        if dest is None:
            raise TransportError(f"node not found: {node_id}")
        return self.cluster.transport.send_message(dest, msg)

    def _broadcast_and_local(self, msg: dict) -> None:
        self.node.receive_message(msg)
        self.node.broadcast(msg)

    # ----------------------------------------------------------- driver

    def _spawn(self) -> None:
        t = threading.Thread(target=self._run,
                             name="rebalance-coordinator", daemon=True)
        self._thread = t
        t.start()

    def stop(self, timeout: float = 5.0) -> None:
        """Server shutdown: halt the driver WITHOUT aborting the plan
        — the persisted cursor resumes it on the next open (the
        crash-and-resume contract, exercised by the acceptance
        soak)."""
        self._halt.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)
        with self._plan_lock:
            self._plan = None

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the driver thread finishes (tests)."""
        t = self._thread
        if t is None:
            return True
        t.join(timeout)
        return not t.is_alive()

    def abort(self) -> None:
        """Operator abort: revert routing to the old topology.  Safe
        at ANY point in the plan — writes stay dual (old owners keep
        committing) until commit, so falling back never loses data."""
        with self._plan_lock:
            if self._plan is None:
                return
        self._abort_requested = True
        self._halt.set()
        t = self._thread
        if t is None or not t.is_alive():
            self._finish_abort()

    def _run(self) -> None:
        with self._plan_lock:
            plan = self._plan
        if plan is None:
            return
        work = [m for m in plan["shards"]
                if m["state"] in (MOVE_DUAL_WRITE, MOVE_BACKFILL)]
        budget = max(1, int(config().transfer_budget))
        qlock = threading.Lock()
        queue = list(work)

        def worker():
            # re-attach the plan's trace on the worker thread: backfill
            # transfers and cutover broadcasts carry its traceparent
            # (resumed pre-trace plans propagate nothing)
            with _tracing.propagate(plan.get("trace")):
                while not self._halt.is_set():
                    with qlock:
                        if not queue:
                            return
                        m = queue.pop(0)
                    try:
                        self._move_shard(m)
                    except Exception:  # noqa: BLE001 — keep resumable
                        bump("rebalance.transfer_failures")
                        # requeue: a shard that did not reach cutover
                        # must NEVER be committed past — retry until it
                        # lands or the operator halts/aborts the plan
                        with qlock:
                            queue.append(m)
                        self._sleep(self._backoff(0))

        threads = [threading.Thread(target=worker,
                                    name=f"rebalance-worker-{i}",
                                    daemon=True)
                   for i in range(min(budget, max(1, len(queue))))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if self._abort_requested:
            self._finish_abort()
            return
        if self._halt.is_set():
            return  # server shutdown: cursor persists, resume later
        with self._plan_lock:
            plan = self._plan
            stuck = [] if plan is None else [
                m for m in plan["shards"]
                if m["state"] not in (MOVE_CUTOVER, MOVE_DROPPED)]
        if stuck:
            # paranoia gate: committing would finalize ownership for a
            # shard whose data never landed — leave the plan live (the
            # cursor persists; resume or abort recovers it)
            bump("rebalance.transfer_failures")
            return
        self._commit()

    def _sleep(self, seconds: float) -> None:
        self._halt.wait(seconds)

    def _backoff(self, attempt: int) -> float:
        cfg = config()
        return min(cfg.backoff_cap, cfg.backoff_base * (2 ** attempt))

    def _move_shard(self, m: dict) -> None:
        """Drive one (index, shard) through backfill -> cutover.  A
        breaker-open transfer target pauses THIS shard with
        exponential backoff; the worker pool keeps other shards
        moving."""
        with self._plan_lock:
            m["state"] = MOVE_BACKFILL
        if _observe.journal_on:
            _observe.emit("rebalance.shard", index=m["index"],
                          shard=m["shard"], state=MOVE_BACKFILL)
        self._persist()
        uris = {n.id: n.uri for n in self.cluster.sorted_nodes()}
        for dest_id, fields in m["dests"].items():
            attempt = 0
            while not self._halt.is_set():
                if self.cluster.breaker_open(dest_id):
                    # the target is known-bad: pause this shard, let
                    # the breaker's half-open trial (or a heartbeat
                    # probe) re-admit it — never abort the plan
                    bump("rebalance.backoffs")
                    self._sleep(self._backoff(attempt))
                    attempt += 1
                    continue
                try:
                    resp = self._send(dest_id, {
                        "type": "rebalance-transfer",
                        "index": m["index"], "shard": m["shard"],
                        "fields": fields, "uris": uris,
                    })
                except ShedByPeerError:
                    self.cluster.note_peer_success(dest_id)
                    bump("rebalance.transfer_failures")
                    self._sleep(self._backoff(attempt))
                    attempt += 1
                    continue
                except (TransportError, OSError):
                    self.cluster.note_peer_failure(dest_id)
                    bump("rebalance.transfer_failures")
                    self._sleep(self._backoff(attempt))
                    attempt += 1
                    continue
                self.cluster.note_peer_success(dest_id)
                if not resp.get("ok"):
                    bump("rebalance.transfer_failures")
                    self._sleep(self._backoff(attempt))
                    attempt += 1
                    continue
                bump("rebalance.bytes_streamed",
                     int(resp.get("bytes", 0)))
                break
        if self._halt.is_set():
            return
        with self._plan_lock:
            m["state"] = MOVE_CUTOVER
        if _observe.journal_on:
            _observe.emit("rebalance.shard", index=m["index"],
                          shard=m["shard"], state=MOVE_CUTOVER)
        self._broadcast_and_local(self._route_for(m) | {
            "type": "rebalance-cutover"})
        bump("rebalance.cutovers")
        self._persist()

    # ----------------------------------------------------- commit/abort

    def _commit(self) -> None:
        """All shards cut over: finalize membership, clear overrides
        everywhere, grace-deferred cleanup of the old copies."""
        c = self.cluster
        with self._plan_lock:
            plan = self._plan
            if plan is None:
                return
            for m in plan["shards"]:
                m["state"] = MOVE_DROPPED
            plan["done"] = True
            remove_id = plan.get("remove_id")
        if _observe.journal_on:
            _observe.emit("rebalance.commit",
                          trace_id=plan.get("trace"),
                          shards=len(plan["shards"]))
        removed_node = None
        if remove_id is not None:
            removed_node = c.node(remove_id)
            c.remove_node(remove_id)
        status = c.to_status()
        self._broadcast_and_local({"type": "rebalance-commit",
                                   "status": status})
        if removed_node is not None:
            try:
                c.transport.send_message(removed_node,
                                         {"type": "node-removed"})
            except TransportError:
                pass
        c._update_cluster_state()
        # propagate global shard availability so the joiner fans
        # queries out over shards it doesn't hold locally, then let
        # the grace-deferred cleaner age out the old copies
        self.node.broadcast_node_status()
        self.node.broadcast({"type": "holder-cleanup"})
        self.node.request_cleanup()
        self._clear_cursor()
        with self._plan_lock:
            self._last = {
                "outcome": "committed",
                "shards": len(plan["shards"]),
                "nodes": plan["new_ids"],
                "at": time.time(),
            }
            self._plan = None
        self._halt.set()

    def _finish_abort(self) -> None:
        with self._plan_lock:
            plan = self._plan
            if plan is None:
                return
            add = plan.get("add")
        msg = {"type": "rebalance-abort",
               "add_id": add["id"] if add else None}
        self.node.receive_message(msg)
        self.node.broadcast(msg)
        bump("rebalance.aborts")
        if _observe.journal_on:
            _observe.emit("rebalance.abort",
                          trace_id=plan.get("trace"),
                          shards=len(plan["shards"]))
        self._clear_cursor()
        with self._plan_lock:
            self._last = {
                "outcome": "aborted",
                "shards": len(plan["shards"]),
                "nodes": plan["old_ids"],
                "at": time.time(),
            }
            self._plan = None
        self.node.broadcast_node_status()
