"""Anti-entropy: periodic replica reconciliation.

Parity target: the reference's holderSyncer (holder.go:880-1101) and
fragmentSyncer (fragment.go:2840-3032): walk the schema; for every
fragment this node owns a replica of, exchange 100-row block checksums
with the other owners, pull block data for differing blocks, and
converge.  Attribute stores reconcile the same way over their own block
checksums (attr.go:80-120, holder.go:975).

Merge semantics: bits converge to the **union** of all replicas
(the reference's mergeBlock computes the union and per-node deltas,
fragment.go:1875-1995 — a cleared bit that some replica still holds is
resurrected there too, absent tombstones).  Deltas this node is missing
are applied locally; deltas a peer is missing are pushed as an import
message to that peer alone.
"""

from __future__ import annotations

from pilosa_tpu.parallel.cluster import TransportError
from pilosa_tpu.serve.admission import tagged
from pilosa_tpu.shardwidth import SHARD_WIDTH


class FragmentSyncer:
    """Reconcile one (index, field, view, shard) across its owner
    replicas (fragment.go:2840 fragmentSyncer)."""

    def __init__(self, node, index: str, field: str, view: str, shard: int):
        self.node = node
        self.cluster = node.cluster
        self.index = index
        self.field = field
        self.view = view
        self.shard = shard

    def _peers(self):
        return [n for n in self.cluster.shard_nodes(self.index, self.shard)
                if n.id != self.cluster.local_id]

    def _local_fragment(self, create: bool = False):
        return self.node.local_fragment(self.index, self.field, self.view,
                                        self.shard, create)

    @tagged("internal")
    def sync(self) -> int:
        """Returns the number of blocks reconciled (0 = replicas
        agree).  Anti-entropy RPC rides the internal class: it can
        shed under query pressure (the next AE round reconverges) but
        can never occupy a query slot on the peer."""
        frag = self._local_fragment()
        local_blocks = {} if frag is None else {
            b["id"]: b["checksum"] for b in frag.blocks()
        }
        peer_blocks: dict[str, dict[int, str]] = {}
        for n in self._peers():
            try:
                resp = self.cluster.transport.send_message(n, {
                    "type": "fragment-blocks",
                    "index": self.index, "field": self.field,
                    "view": self.view, "shard": self.shard,
                })
            except TransportError:
                continue
            peer_blocks[n.id] = {
                b["id"]: b["checksum"] for b in resp.get("blocks", [])
            }
        # blocks needing reconciliation: checksum differs anywhere
        dirty = set()
        all_ids = set(local_blocks)
        for blocks in peer_blocks.values():
            all_ids |= set(blocks)
        for bid in all_ids:
            sums = {local_blocks.get(bid)}
            for blocks in peer_blocks.values():
                sums.add(blocks.get(bid))
            if len(sums) > 1:
                dirty.add(bid)
        for bid in sorted(dirty):
            self._sync_block(bid, list(peer_blocks))
        return len(dirty)

    def _sync_block(self, block: int, peer_ids: list[str]) -> None:
        """Pull every replica's block data, compute the union, apply the
        local diff, and push each peer its own missing bits
        (fragment.go:2941 syncBlock + :1875 mergeBlock)."""
        frag = self._local_fragment(create=True)
        local_pairs = set(zip(*frag.block_data(block)))
        per_peer: dict[str, set] = {}
        for n in self._peers():
            if n.id not in peer_ids:
                continue
            try:
                resp = self.cluster.transport.send_message(n, {
                    "type": "fragment-block-data",
                    "index": self.index, "field": self.field,
                    "view": self.view, "shard": self.shard, "block": block,
                })
            except TransportError:
                continue
            per_peer[n.id] = set(zip(resp.get("rowIDs", []),
                                     resp.get("columnIDs", [])))
        union = set(local_pairs)
        for pairs in per_peer.values():
            union |= pairs
        # local diff
        missing = union - local_pairs
        if missing:
            frag.import_positions(
                [r * SHARD_WIDTH + c for r, c in missing])
        # push per-peer diffs (view-aware fragment import so time and BSI
        # views reconcile too, not just the standard view)
        for n in self._peers():
            pairs = per_peer.get(n.id)
            if pairs is None:
                continue
            peer_missing = union - pairs
            if not peer_missing:
                continue
            try:
                self.cluster.transport.send_message(n, {
                    "type": "fragment-import",
                    "index": self.index, "field": self.field,
                    "view": self.view, "shard": self.shard,
                    "positions": [r * SHARD_WIDTH + c
                                  for r, c in peer_missing],
                })
            except TransportError:
                pass


class HolderSyncer:
    """Walk the whole schema and reconcile every locally-owned fragment
    and attribute store (holder.go:880 holderSyncer.SyncHolder)."""

    def __init__(self, node):
        self.node = node
        self.cluster = node.cluster

    @tagged("internal")
    def sync_holder(self) -> int:
        if self.cluster.replica_n < 2:
            return 0
        from pilosa_tpu.parallel.cluster import STATE_RESIZING

        if self.cluster.state == STATE_RESIZING:
            return 0  # skipped mid-resize (server.go:514)
        # announce local shard availability first so peers (owners or
        # not) fan queries out over everything this node holds
        # (reference NodeStatus exchange, server.go:569)
        self.node.broadcast_node_status()
        total = 0
        for idx_info in self.node.holder.schema():
            iname = idx_info["name"]
            idx = self.node.holder.index(iname)
            if idx is None:
                continue
            self._sync_attrs(iname, None)
            for f in idx.all_fields():
                self._sync_attrs(iname, f.name)
                for vname, view in list(f.views.items()):
                    for shard in sorted(f.available_shards()):
                        if not self.cluster.owns_shard(
                                self.cluster.local_id, iname, shard):
                            continue
                        total += FragmentSyncer(
                            self.node, iname, f.name, vname, shard).sync()
        # periodic unowned-fragment cleanup rides the AE cadence, so a
        # node that missed the one-shot post-resize holder-cleanup
        # broadcast still converges (reference holderCleaner loop,
        # holder.go:1103) — grace-deferred like every cleanup path,
        # or a short AE interval re-opens the read-vs-cleanup race
        # the grace exists to close
        self.node.request_cleanup()
        # replicas tail the primary's key-translation entry stream
        # (reference holder.go:690-878)
        self.node.tail_translate_entries()
        return total

    def _sync_attrs(self, index: str, field: str | None) -> None:
        """Pull attribute blocks that differ and merge them locally
        (holder.go:975 syncIndex / :1021 syncField; attrBlocks.Diff
        attr.go:90)."""
        store = self.node.attr_store(index, field)
        if store is None:
            return
        for n in self.cluster.sorted_nodes():
            if n.id == self.cluster.local_id:
                continue
            try:
                resp = self.cluster.transport.send_message(n, {
                    "type": "attr-blocks", "index": index, "field": field,
                })
                peer_blocks = [(b["id"], bytes.fromhex(b["checksum"]))
                               for b in resp.get("blocks", [])]
                need = store.blocks_diff(peer_blocks)
                for bid in need:
                    data = self.cluster.transport.send_message(n, {
                        "type": "attr-block-data", "index": index,
                        "field": field, "block": bid,
                    }).get("attrs", {})
                    store.set_bulk_attrs(
                        {int(k): v for k, v in data.items()})
            except TransportError:
                continue
