"""Anti-entropy: incremental, failure-classified replica reconciliation.

Parity target: the reference's holderSyncer (holder.go:880-1101) and
fragmentSyncer (fragment.go:2840-3032): walk the schema; for every
fragment this node owns a replica of, exchange 100-row block checksums
with the other owners, pull block data for differing blocks, and
converge.  Attribute stores reconcile the same way over their own block
checksums (attr.go:80-120, holder.go:975).

Merge semantics: bits converge to the **union** of all replicas
(the reference's mergeBlock computes the union and per-node deltas,
fragment.go:1875-1995 — a cleared bit that some replica still holds is
resurrected there too, absent tombstones).  Deltas this node is missing
are applied locally; deltas a peer is missing are pushed as an import
message to that peer alone.

The self-healing round (PR 14) turned the bare synchronous walk into a
subsystem:

- **Digest caching** — fragment block checksums are generation-keyed
  (``Fragment.blocks_with_flag``): an unchanged fragment costs zero
  checksum work on either side of the exchange, so a quiescent round
  is pure cheap RPC (and zero block-data RPCs, since nothing differs).
- **Time-sliced rounds** — ``sync_holder(budget_s)`` walks from a
  resumable (index, field, view, shard) cursor persisted on the node
  and stops when the slice budget is spent; the next round resumes,
  so a huge holder never monopolizes the internal admission class.
- **Breaker-aware peer skip** — a peer whose circuit breaker is open
  is skipped without an RPC (``ae.peer_skipped``) instead of paying a
  full transport timeout per fragment; transport failures feed the
  breaker, and a shed reply is proof of life exactly as on the read
  path (ShedByPeerError never opens a breaker).
- **Failure classification** — peer failures that the old walk
  swallowed (``except TransportError: pass``) are classified
  (transport / shed / refused), counted under the ``ae.*`` family, and
  carried in the round result instead of reporting a clean round.
- **Deadline-bounded exchanges** — every peer RPC (fragment blocks,
  block data, pushes, attribute exchanges) runs under a per-exchange
  deadline scope (``[anti-entropy] peer-timeout``), the internal-class
  deadline pattern, so one hung peer cannot stall the whole round.

Round outcomes land on ``node.ae_last_round`` (the /debug/antientropy
document), the ``ae.*`` gauges, and — when a flight recorder is
attached — an internal-class record on /debug/queries.
"""

from __future__ import annotations

import time

from pilosa_tpu import lockcheck as _lockcheck
from pilosa_tpu import observe as _observe
from pilosa_tpu import tracing as _tracing
from pilosa_tpu.parallel.cluster import ShedByPeerError, TransportError
from pilosa_tpu.serve import deadline as _deadline
from pilosa_tpu.serve.admission import tagged
from pilosa_tpu.shardwidth import SHARD_WIDTH

#: per-peer-exchange deadline (seconds) when none is configured
DEFAULT_PEER_TIMEOUT_S = 2.0

# --------------------------------------------------------------------
# ae.* counters (published as gauges at scrape time, like tape.*)
# --------------------------------------------------------------------

_lock = _lockcheck.lock("syncer-counters")
_counters = {
    "ae.rounds": 0,            # completed full-holder walks
    "ae.slices": 0,            # sync_holder calls (incl. partial)
    "ae.fragments": 0,         # fragment syncs performed
    "ae.dirty_blocks": 0,      # blocks that differed somewhere
    "ae.reconciled": 0,        # blocks merged to the union
    "ae.pushed": 0,            # per-peer diff pushes delivered
    "ae.pulled": 0,            # peer block-data pulls applied
    "ae.peer_skipped": 0,      # peers skipped on an open breaker
    "ae.failures_transport": 0,
    "ae.failures_shed": 0,
    "ae.failures_refused": 0,
    "ae.digest_cache_hits": 0,
    "ae.digest_cache_misses": 0,
}


def bump(name: str, value: int = 1) -> None:
    with _lock:
        _counters[name] += value


def counters() -> dict:
    with _lock:
        return dict(_counters)


def note_digest(hit: bool) -> None:
    """One fragment checksum request served (either side of the
    exchange): from the generation-keyed cache, or recomputed."""
    bump("ae.digest_cache_hits" if hit else "ae.digest_cache_misses")


def publish_gauges(stats) -> None:
    """ae.* gauge family for /metrics and /debug/vars — published
    unconditionally (zeros on a clean server)."""
    for name, v in counters().items():
        stats.gauge(name, v)


class SyncStats:
    """One round's accounting, carried in the round result instead of
    the old walk's silent ``pass``."""

    __slots__ = ("fragments", "dirty", "reconciled", "pushed", "pulled",
                 "peer_skipped", "digest_hits", "digest_misses",
                 "failures", "attr_failures", "block_data_rpcs")

    def __init__(self):
        self.fragments = 0
        self.dirty = 0
        self.reconciled = 0
        self.pushed = 0
        self.pulled = 0
        self.peer_skipped = 0
        self.digest_hits = 0
        self.digest_misses = 0
        self.failures = {"transport": 0, "shed": 0, "refused": 0}
        self.attr_failures = {"transport": 0, "shed": 0, "refused": 0}
        self.block_data_rpcs = 0

    def note_failure(self, kind: str, attrs: bool = False) -> None:
        (self.attr_failures if attrs else self.failures)[kind] += 1
        bump(f"ae.failures_{kind}")

    def to_dict(self) -> dict:
        return {
            "fragments": self.fragments,
            "dirtyBlocks": self.dirty,
            "reconciled": self.reconciled,
            "pushed": self.pushed,
            "pulled": self.pulled,
            "peerSkipped": self.peer_skipped,
            "digestCacheHits": self.digest_hits,
            "digestCacheMisses": self.digest_misses,
            "blockDataRpcs": self.block_data_rpcs,
            "failures": dict(self.failures),
            "attrFailures": dict(self.attr_failures),
        }


def classify_failure(exc: BaseException) -> str:
    """transport / shed / refused — the three ways a peer exchange
    fails (a refusal is a structured non-ok reply, e.g. unowned)."""
    if isinstance(exc, ShedByPeerError):
        return "shed"
    if isinstance(exc, (TransportError, _deadline.DeadlineExceededError,
                        TimeoutError, OSError)):
        return "transport"
    return "refused"


class FragmentSyncer:
    """Reconcile one (index, field, view, shard) across its owner
    replicas (fragment.go:2840 fragmentSyncer)."""

    def __init__(self, node, index: str, field: str, view: str,
                 shard: int, stats: SyncStats | None = None,
                 peer_timeout: float | None = None):
        self.node = node
        self.cluster = node.cluster
        self.index = index
        self.field = field
        self.view = view
        self.shard = shard
        self.stats = stats if stats is not None else SyncStats()
        self.peer_timeout = (DEFAULT_PEER_TIMEOUT_S
                             if peer_timeout is None else peer_timeout)

    def _peers(self):
        return [n for n in self.cluster.shard_nodes(self.index, self.shard)
                if n.id != self.cluster.local_id]

    def _available_peers(self):
        """Owner peers whose breaker is not open: a known-dead peer
        must not cost a transport timeout per fragment — the breaker's
        half-open trial (or a heartbeat probe) re-admits it."""
        out = []
        for n in self._peers():
            if self.cluster.breaker_open(n.id):
                self.stats.peer_skipped += 1
                bump("ae.peer_skipped")
                continue
            out.append(n)
        return out

    def _local_fragment(self, create: bool = False):
        return self.node.local_fragment(self.index, self.field, self.view,
                                        self.shard, create)

    def _exchange(self, n, message: dict) -> dict:
        """One deadline-bounded peer RPC with breaker feedback: a shed
        reply is proof of life (note_peer_success), a transport error
        feeds the peer's breaker.  Raises the original exception —
        callers classify and account it."""
        try:
            with _deadline.scope(_deadline.Deadline(self.peer_timeout)):
                resp = self.cluster.transport.send_message(n, message)
        except ShedByPeerError:
            self.cluster.note_peer_success(n.id)
            raise
        except (TransportError, _deadline.DeadlineExceededError,
                TimeoutError, OSError):
            self.cluster.note_peer_failure(n.id)
            raise
        self.cluster.note_peer_success(n.id)
        return resp

    @tagged("internal")
    def sync(self) -> int:
        """Returns the number of blocks reconciled (0 = replicas
        agree).  Anti-entropy RPC rides the internal class: it can
        shed under query pressure (the next AE round reconverges) but
        can never occupy a query slot on the peer."""
        self.stats.fragments += 1
        bump("ae.fragments")
        frag = self._local_fragment()
        local_blocks = {}
        if frag is not None:
            blocks, hit = frag.blocks_with_flag()
            note_digest(hit)
            if hit:
                self.stats.digest_hits += 1
            else:
                self.stats.digest_misses += 1
            local_blocks = {b["id"]: b["checksum"] for b in blocks}
        peer_blocks: dict[str, dict[int, str]] = {}
        for n in self._available_peers():
            try:
                resp = self._exchange(n, {
                    "type": "fragment-blocks",
                    "index": self.index, "field": self.field,
                    "view": self.view, "shard": self.shard,
                })
            except Exception as e:  # noqa: BLE001 — classified, counted
                self.stats.note_failure(classify_failure(e))
                continue
            peer_blocks[n.id] = {
                b["id"]: b["checksum"] for b in resp.get("blocks", [])
            }
        # blocks needing reconciliation: checksum differs anywhere
        dirty = set()
        all_ids = set(local_blocks)
        for blocks in peer_blocks.values():
            all_ids |= set(blocks)
        for bid in all_ids:
            sums = {local_blocks.get(bid)}
            for blocks in peer_blocks.values():
                sums.add(blocks.get(bid))
            if len(sums) > 1:
                dirty.add(bid)
        self.stats.dirty += len(dirty)
        bump("ae.dirty_blocks", len(dirty))
        reconciled = 0
        for bid in sorted(dirty):
            if self._sync_block(bid, list(peer_blocks)):
                reconciled += 1
        # only blocks whose merge saw NO peer failure count as
        # reconciled — a round that pulled/pushed nothing must not
        # read as repaired (dirtyBlocks vs reconciled is the gap)
        self.stats.reconciled += reconciled
        bump("ae.reconciled", reconciled)
        return len(dirty)

    def _sync_block(self, block: int, peer_ids: list[str]) -> bool:
        """Pull every replica's block data, compute the union, apply the
        local diff, and push each peer its own missing bits
        (fragment.go:2941 syncBlock + :1875 mergeBlock).  Peer failures
        are classified and counted — never silently swallowed.  Returns
        True only when every exchange in the merge succeeded."""
        frag = self._local_fragment(create=True)
        local_pairs = set(zip(*frag.block_data(block)))
        per_peer: dict[str, set] = {}
        ok = True
        for n in self._peers():
            if n.id not in peer_ids:
                continue
            try:
                self.stats.block_data_rpcs += 1
                resp = self._exchange(n, {
                    "type": "fragment-block-data",
                    "index": self.index, "field": self.field,
                    "view": self.view, "shard": self.shard, "block": block,
                })
            except Exception as e:  # noqa: BLE001 — classified, counted
                self.stats.note_failure(classify_failure(e))
                ok = False
                continue
            per_peer[n.id] = set(zip(resp.get("rowIDs", []),
                                     resp.get("columnIDs", [])))
            self.stats.pulled += 1
            bump("ae.pulled")
        union = set(local_pairs)
        for pairs in per_peer.values():
            union |= pairs
        # local diff
        missing = union - local_pairs
        if missing:
            frag.import_positions(
                [r * SHARD_WIDTH + c for r, c in missing])
        # push per-peer diffs (view-aware fragment import so time and BSI
        # views reconcile too, not just the standard view)
        for n in self._peers():
            pairs = per_peer.get(n.id)
            if pairs is None:
                continue
            peer_missing = union - pairs
            if not peer_missing:
                continue
            try:
                resp = self._exchange(n, {
                    "type": "fragment-import",
                    "index": self.index, "field": self.field,
                    "view": self.view, "shard": self.shard,
                    "positions": [r * SHARD_WIDTH + c
                                  for r, c in peer_missing],
                })
            except Exception as e:  # noqa: BLE001 — classified, counted
                self.stats.note_failure(classify_failure(e))
                ok = False
                continue
            if resp.get("ok", True):
                self.stats.pushed += 1
                bump("ae.pushed")
            else:
                self.stats.note_failure("refused")
                ok = False
        return ok


class HolderSyncer:
    """Walk the whole schema and reconcile every locally-owned fragment
    and attribute store (holder.go:880 holderSyncer.SyncHolder), in
    resumable time slices."""

    def __init__(self, node, peer_timeout: float | None = None):
        self.node = node
        self.cluster = node.cluster
        self.peer_timeout = (DEFAULT_PEER_TIMEOUT_S
                             if peer_timeout is None else peer_timeout)

    # --------------------------------------------------------- the walk

    def _work_items(self) -> list[tuple]:
        """The full ordered reconcile walk.  Each item carries a
        monotonically increasing sort key so the resumable cursor is a
        plain tuple comparison — schema churn between slices degrades
        to skipping/revisiting a few items, never corruption:

        - ``(iname, "",    0, "", -1)`` — index attribute store
        - ``(iname, fname, 0, "", -1)`` — field attribute store
        - ``(iname, fname, 1, vname, shard)`` — one fragment
        """
        items: list[tuple] = []
        for idx_info in sorted(self.node.holder.schema(),
                               key=lambda d: d["name"]):
            iname = idx_info["name"]
            idx = self.node.holder.index(iname)
            if idx is None:
                continue
            items.append(((iname, "", 0, "", -1), "attrs", iname, None))
            for f in sorted(idx.all_fields(), key=lambda f: f.name):
                items.append(((iname, f.name, 0, "", -1),
                              "attrs", iname, f.name))
                for vname in sorted(f.views):
                    for shard in sorted(f.available_shards()):
                        if not self.cluster.owns_shard(
                                self.cluster.local_id, iname, shard):
                            continue
                        items.append(((iname, f.name, 1, vname, shard),
                                      "frag", iname, f.name, vname,
                                      shard))
        return items

    @tagged("internal")
    def sync_holder(self, budget_s: float | None = None) -> int:
        """One reconcile slice.  With no budget (the default, and the
        historical call shape) the whole holder is walked; with a
        budget the walk stops when the slice is spent and persists its
        cursor on the node — the next call resumes there.  Returns the
        number of blocks reconciled in THIS slice."""
        if self.cluster.replica_n < 2:
            return 0
        from pilosa_tpu.parallel.cluster import STATE_RESIZING

        if self.cluster.state == STATE_RESIZING:
            return 0  # skipped mid-resize (server.go:514)
        # AE originates inside the cluster: mint a round trace so every
        # checksum/pull/push exchange this slice issues carries ONE
        # joinable traceparent across the peers it touches
        with _tracing.propagate(_tracing.active_trace_id()
                                or _tracing.new_trace_id()):
            return self._sync_holder_traced(budget_s)

    def _sync_holder_traced(self, budget_s: float | None) -> int:
        t0 = time.monotonic()
        stats = SyncStats()
        bump("ae.slices")
        if _observe.journal_on:
            _observe.emit("ae.round.start")
        cursor = getattr(self.node, "ae_cursor", None)
        fresh = cursor is None
        if fresh:
            # announce local shard availability first so peers (owners
            # or not) fan queries out over everything this node holds
            # (reference NodeStatus exchange, server.go:569)
            self.node.broadcast_node_status()
        items = self._work_items()
        if cursor is not None:
            resumed = [it for it in items if it[0] > tuple(cursor)]
            if not resumed:
                # the cursor outlived its schema position: restart
                fresh = True
                self.node.broadcast_node_status()
            else:
                items = resumed
        deadline = (None if not budget_s or budget_s <= 0
                    else t0 + budget_s)
        total = 0
        completed = True
        last_key = cursor
        processed = 0
        for it in items:
            # minimum-progress guarantee: at least one item per slice,
            # or a budget smaller than the walk's setup cost would park
            # the cursor in place forever and AE would silently stop
            # converging
            if (processed and deadline is not None
                    and time.monotonic() >= deadline):
                completed = False
                break
            key = it[0]
            if it[1] == "attrs":
                self._sync_attrs(it[2], it[3], stats)
            else:
                _, _, iname, fname, vname, shard = it
                total += FragmentSyncer(
                    self.node, iname, fname, vname, shard,
                    stats=stats, peer_timeout=self.peer_timeout).sync()
            processed += 1
            last_key = key
        if completed:
            self.node.ae_cursor = None
            bump("ae.rounds")
            if _observe.journal_on:
                _observe.emit("ae.round.converge",
                              reconciled=total,
                              dirty=stats.dirty)
        else:
            self.node.ae_cursor = last_key
            if _observe.journal_on:
                # budget spent mid-walk: the cursor parks for the next
                # slice to resume from
                _observe.emit("ae.round.park",
                              cursor=list(last_key or []),
                              reconciled=total)
        # cleanup + translate tailing run on EVERY slice, not just a
        # completed round: neither is part of the reconcile walk being
        # sliced, and deferring them to round completion would
        # multiply their cadence by the slice count under a small
        # round-budget.  Unowned-fragment cleanup rides the AE cadence
        # so a node that missed the one-shot post-resize cleanup
        # broadcast still converges (reference holderCleaner loop,
        # holder.go:1103) — grace-deferred like every cleanup path;
        # replicas tail the primary's key-translation entry stream
        # (reference holder.go:690-878)
        self.node.request_cleanup()
        self.node.tail_translate_entries()
        self._publish_round(stats, t0, completed, fresh)
        return total

    def _publish_round(self, stats: SyncStats, t0: float,
                       completed: bool, fresh: bool) -> None:
        """Round outcome -> node state (/debug/antientropy) and, when
        a flight recorder is attached, an internal-class record on
        /debug/queries."""
        out = stats.to_dict()
        out.update({
            "durationMs": round((time.monotonic() - t0) * 1e3, 3),
            "completed": completed,
            "resumed": not fresh,
            "cursor": (None if completed
                       else list(getattr(self.node, "ae_cursor", None)
                                 or [])),
            "at": time.time(),
        })
        self.node.ae_last_round = out
        recorder = getattr(self.node.executor, "recorder", None)
        if recorder is None or not recorder.enabled:
            return
        summary = (f"AntiEntropy(fragments={stats.fragments}, "
                   f"dirty={stats.dirty}, pushed={stats.pushed}, "
                   f"failures={sum(stats.failures.values())}, "
                   f"completed={str(completed).lower()})")
        rec = recorder.begin("", summary)
        rec.admission = {"class": "internal", "queue_wait_ns": 0}
        rec.note_path("anti-entropy")
        failed = (sum(stats.failures.values())
                  + sum(stats.attr_failures.values()))
        recorder.publish(
            rec, error=(f"{failed} peer exchanges failed"
                        if failed else None))

    def _sync_attrs(self, index: str, field: str | None,
                    stats: SyncStats) -> None:
        """Pull attribute blocks that differ and merge them locally
        (holder.go:975 syncIndex / :1021 syncField; attrBlocks.Diff
        attr.go:90).  Each peer exchange is deadline-bounded (the
        internal-class deadline pattern the fragment walk rides) so a
        hung peer costs at most peer-timeout, never a stalled round;
        failures are classified and counted, never swallowed."""
        store = self.node.attr_store(index, field)
        if store is None:
            return
        for n in self.cluster.sorted_nodes():
            if n.id == self.cluster.local_id:
                continue
            if self.cluster.breaker_open(n.id):
                stats.peer_skipped += 1
                bump("ae.peer_skipped")
                continue
            try:
                # one FRESH deadline per RPC (matching _exchange on
                # the fragment walk) — a single budget spanning the
                # attr-blocks exchange plus every block-data pull
                # would charge a healthy peer with many differing
                # blocks a cumulative timeout and feed its breaker
                with _deadline.scope(
                        _deadline.Deadline(self.peer_timeout)):
                    resp = self.cluster.transport.send_message(n, {
                        "type": "attr-blocks", "index": index,
                        "field": field,
                    })
                peer_blocks = [(b["id"],
                                bytes.fromhex(b["checksum"]))
                               for b in resp.get("blocks", [])]
                need = store.blocks_diff(peer_blocks)
                for bid in need:
                    with _deadline.scope(
                            _deadline.Deadline(self.peer_timeout)):
                        data = self.cluster.transport.send_message(n, {
                            "type": "attr-block-data", "index": index,
                            "field": field, "block": bid,
                        }).get("attrs", {})
                    store.set_bulk_attrs(
                        {int(k): v for k, v in data.items()})
            except Exception as e:  # noqa: BLE001 — classified, counted
                # EVERY failure is classified (matching the fragment
                # walk): an uncaught malformed-reply or remote error
                # would abort the whole round mid-walk and park every
                # later item unreconciled, forever
                kind = classify_failure(e)
                if isinstance(e, ShedByPeerError):
                    self.cluster.note_peer_success(n.id)
                elif kind == "transport":
                    self.cluster.note_peer_failure(n.id)
                stats.note_failure(kind, attrs=True)
