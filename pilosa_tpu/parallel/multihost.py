"""Multi-host bootstrap: one SPMD mesh spanning TPU pods over ICI/DCN.

The reference scales across machines with its NCCL-free HTTP
scatter-gather (executor.go:2455); the TPU-native equivalent keeps TWO
planes, per SURVEY.md §5:

- **data plane**: `jax.distributed` + a `Mesh` over every chip of every
  host — XLA routes `psum`/all-reduce over ICI within a slice and DCN
  between slices.  The same `parallel/mesh.py` programs run unchanged;
  only device enumeration differs (``jax.devices()`` is global after
  `initialize`).
- **control plane**: the HTTP cluster (membership, DDL, AE, resize)
  stays as-is — one `pilosa_tpu` server process per TPU host, each
  owning the shards whose stacks live on its local chips.

``initialize`` wraps `jax.distributed.initialize` with the env-var
conventions used by TPU launchers; ``global_mesh`` builds the shard
mesh over all processes' devices.  A single-process call (the default)
is a no-op bootstrap over local devices, so every code path here is
exercised by ordinary CI (`tests/test_multihost.py`); real multi-pod
runs only change the env vars.
"""

from __future__ import annotations

import os

_initialized = False
_initialized_distributed = False


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """Join the global jax runtime.  Arguments fall back to the
    standard launcher env vars (JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID); values absent everywhere stay
    ``None`` so `jax.distributed.initialize` auto-detects them from
    the platform (Cloud TPU metadata sets process count/id itself).
    With no configuration at all this is a local no-op bootstrap, so
    the same server entry point works on a laptop and on a pod
    slice."""
    global _initialized, _initialized_distributed
    coordinator_address = (coordinator_address
                           or os.environ.get("JAX_COORDINATOR_ADDRESS"))
    if num_processes is None:
        env_np = os.environ.get("JAX_NUM_PROCESSES")
        num_processes = int(env_np) if env_np else None
    if process_id is None:
        env_pid = os.environ.get("JAX_PROCESS_ID")
        process_id = int(env_pid) if env_pid else None
    wants_distributed = (coordinator_address is not None
                         or (num_processes or 1) > 1)
    if _initialized:
        if wants_distributed and not _initialized_distributed:
            raise RuntimeError(
                "multihost.initialize() was already completed as a "
                "single-host bootstrap (an argless helper ran first); "
                "the distributed join must be the FIRST call")
        return
    if not wants_distributed:
        _initialized = True  # single host: local devices are the world
        return
    import jax

    try:
        from jax._src import xla_bridge

        if getattr(xla_bridge, "_backends", None):
            raise RuntimeError(
                "multihost.initialize() must run before any JAX "
                "computation — call it first thing in the launcher "
                "(cmd.run_server does) so jax.distributed can join the "
                "global runtime before backends initialize")
    except ImportError:  # private module moved: let jax raise its own
        pass
    # Failure-detection latency: a process death mid-collective is
    # fail-stop for every participant (the coordination service
    # terminates survivors after heartbeat_timeout_seconds — see
    # spmd.try_collective), so the heartbeat window IS the bound on
    # how long a broken world can park queries.  Default 100 s
    # (jax's); operators running the collective plane trade detection
    # latency against false positives here.
    kwargs = {}
    hb = os.environ.get("PILOSA_TPU_DIST_HEARTBEAT_S")
    if hb:
        kwargs["heartbeat_timeout_seconds"] = int(hb)
    init_to = os.environ.get("PILOSA_TPU_DIST_INIT_TIMEOUT_S")
    if init_to:
        kwargs["initialization_timeout"] = int(init_to)
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )
    _initialized = True
    _initialized_distributed = True


def global_mesh(axis_name: str | None = None):
    """The shard mesh over EVERY process's devices.  After
    ``initialize`` on n hosts, ``jax.devices()`` enumerates all chips;
    the 1-D shard axis therefore spans hosts and XLA places collectives
    on ICI within a slice and DCN across slices (the scaling-book
    recipe: pick the mesh, annotate shardings, let XLA insert the
    collectives)."""
    from pilosa_tpu.parallel import mesh as pmesh

    initialize()
    return pmesh.device_mesh(
        axis_name=pmesh.SHARD_AXIS if axis_name is None else axis_name)


def process_info() -> dict:
    """(process_index, process_count, local/global device counts) — the
    node-identity surface a launcher or /status endpoint reports."""
    import jax

    initialize()
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def local_shard_slice(n_shards: int) -> range:
    """DEPRECATED naive partition: a contiguous block of the shard
    space per process, kept only for standalone mesh experiments that
    have no cluster.  Product code must NOT use this — it contradicts
    the control plane's jump-hash fragment placement.  The reconciled
    layout is `parallel/spmd.py`'s Plan: the global shard axis is
    ordered by (owning process rank, shard id) DERIVED from the jump
    hash, so each process's mesh blocks hold exactly the fragments its
    disks own (VERDICT round-2 missing #2, resolved round 3)."""
    import jax

    initialize()
    per = -(-n_shards // jax.process_count())
    start = jax.process_index() * per
    return range(start, min(start + per, n_shards))
