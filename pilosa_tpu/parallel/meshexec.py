"""Mesh-native fused execution: the serving path's device-mesh SPMD
layer.

Before this module the modern engines — fused expression programs
(ops/expr.py), ragged op-tape batches (ops/tape.py), compressed
container gathers (ops/containers.py) — each ran as ONE launch, but
that launch landed on a single device (or leaned on XLA's implicit
GSPMD propagation when stacks happened to be sharded).  The
reference's only scale-out is host map-reduce over shards
(executor.go:2455), and our port mirrored it above the device.  This
module replaces that with the DrJAX shape (PAPERS.md 2403.07128):
map-reduce expressed as sharded one-launch JAX programs —

- **Layout** — the shard axis of every fused operand (dense row
  stacks, delta planes, tape register batches, container gather
  domains) lays out across a named 1-D ``jax.sharding.Mesh`` via
  ``NamedSharding``; container word pools replicate (gather indices
  cross shard boundaries by construction).  Placement is the shard
  plan: shard-axis row *i* lives on device ``i // (rows/axis)``, and
  ``models/field.py`` pads the axis to a multiple of the mesh size so
  blocks split evenly.
- **Execution** — the three fused dispatch paths compile
  ``shard_map`` variants of their programs: per-device blocks run the
  identical tree/tape/gather body, and per-shard popcounts return
  through a tiled ``lax.all_gather`` on the shard axis (the
  mesh-native analog of the host-side per-shard result gather;
  ``parallel/mesh.py`` keeps the scalar ``psum`` reductions the
  collective/spmd plane uses).  One launch therefore evaluates a
  query — or a whole coalesced megabatch — across every local chip.
- **Fallbacks** — ``[mesh] enabled=false`` and the per-request
  ``?nomesh=1`` escape route placement to a single device and
  execution through the exact pre-mesh jit programs (byte-identical,
  regression-pinned); host mode (one CPU device) and multi-process
  deployments (``parallel/spmd.py`` owns the cross-process mesh) are
  never mesh-active.

Process-wide configuration mirrors ``[containers]``: ``configure``
applies explicit values in place, the FIRST server to ``retain()``
captures the pre-server baseline and the LAST ``release()`` restores
it (pilosa-lint P5).
"""

from __future__ import annotations

import threading
from typing import Any

import numpy as np

#: The one data axis of a bitmap index (SURVEY.md §2.5: sharding is
#: the reference's entire parallelism strategy) — shared with
#: parallel/mesh.py's collective programs.
SHARD_AXIS = "shards"


# ------------------------------------------------------------ runtime config


class MeshRuntimeConfig:
    """The process-wide [mesh] knobs (one per process, like the
    [containers] runtime config).  ``enabled`` is tri-state like the
    coalescer's: ``"auto"`` activates the mesh exactly when it can
    help — more than one local device, one process (multi-process
    fan-out belongs to parallel/spmd.py), not host mode.
    ``axis_size`` bounds how many local devices join the shard axis
    (0 = all of them)."""

    __slots__ = ("enabled", "axis_size")

    def __init__(self) -> None:
        self.enabled: Any = "auto"
        self.axis_size = 0


_cfg = MeshRuntimeConfig()
_cfg_lock = threading.Lock()
_baseline: tuple | None = None
_refs = 0
#: (axis_size, device ids) -> Mesh — meshes are cached singletons so
#: program caches keyed on the Mesh object stay warm across queries.
_mesh_cache: dict = {}


def config() -> MeshRuntimeConfig:
    return _cfg


def configure(enabled=None, axis_size: int | None = None) -> MeshRuntimeConfig:
    """Apply [mesh] config in place — only explicit values land, so a
    second in-process server cannot wipe the first's settings with
    defaults (same contract as containers.configure)."""
    if enabled is not None and not isinstance(enabled, bool):
        # validate at the CONFIGURATION site, where a raise reaches
        # the operator (server construction / CLI startup): stored
        # unchecked, a typo like "ture" would only surface as
        # axis_size() quietly returning 1 — a silently-disabled mesh
        # indistinguishable from enabled=false
        s = str(enabled).strip().lower()
        if s not in ("1", "true", "yes", "on",
                     "0", "false", "no", "off", "auto"):
            raise ValueError(
                f"mesh.enabled must be auto/true/false, got {enabled!r}")
    with _cfg_lock:
        if enabled is not None:
            _cfg.enabled = enabled
        if axis_size is not None:
            _cfg.axis_size = int(axis_size)
    return _cfg


def retain() -> None:
    """Take a server reference; the FIRST holder snapshots the
    pre-server baseline config (restore composes correctly under any
    close order — the PR-6 [ingest] lesson, pilosa-lint P5)."""
    global _refs, _baseline
    with _cfg_lock:
        if _refs == 0 and _baseline is None:
            _baseline = (_cfg.enabled, _cfg.axis_size)
        _refs += 1


def release() -> None:
    """Drop a server reference; the LAST holder restores the captured
    baseline for every other user of the process."""
    global _refs, _baseline
    with _cfg_lock:
        if _refs > 0:
            _refs -= 1
        if _refs == 0 and _baseline is not None:
            _cfg.enabled, _cfg.axis_size = _baseline
            _baseline = None


def reset() -> MeshRuntimeConfig:
    """Restore defaults, drop any held baseline and cached meshes
    (tests)."""
    global _cfg, _baseline, _refs
    with _cfg_lock:
        _cfg = MeshRuntimeConfig()
        _baseline = None
        _refs = 0
        _mesh_cache.clear()
    return _cfg


def resolve_enabled(mode) -> bool:
    """``auto`` | true | false — TOML booleans and env strings both
    accepted; a typo raises instead of silently meaning auto (the
    coalescer.resolve_enabled contract)."""
    if isinstance(mode, bool):
        return mode
    s = str(mode).strip().lower()
    if s in ("1", "true", "yes", "on"):
        return True
    if s in ("0", "false", "no", "off"):
        return False
    if s != "auto":
        raise ValueError(
            f"mesh.enabled must be auto/true/false, got {mode!r}")
    return _eligible()


def _eligible() -> bool:
    """Can a mesh help in this process at all?  More than one LOCAL
    device, single process (the multi-process global mesh belongs to
    parallel/spmd.py's collective plans), and not host mode (one CPU
    device runs the numpy/native engine — there is nothing to
    shard)."""
    import jax

    from pilosa_tpu.ops import bitmap as bm

    if bm.host_mode():
        return False
    if jax.process_count() > 1:
        return False
    return len(jax.local_devices()) > 1


def axis_size() -> int:
    """The shard-axis size in force: ``[mesh] axis-size`` clamped to
    the local device count (0 = all local devices).  1 when the mesh
    cannot activate."""
    if not _eligible():
        return 1
    try:
        if not resolve_enabled(_cfg.enabled):
            return 1
    except ValueError:
        return 1
    import jax

    n = len(jax.local_devices())
    want = _cfg.axis_size
    if want and want > 0:
        n = min(n, want)
    return max(1, n)


def active() -> bool:
    """True when fused dispatches route the shard_map mesh programs."""
    return axis_size() > 1


def active_mesh():
    """The active 1-D device mesh, or None when mesh execution is off
    (disabled, single device, host mode, or multi-process).  Cached
    per (axis size, device ids) so the Mesh object — which keys the
    compiled mesh-program caches — is a stable singleton."""
    n = axis_size()
    if n <= 1:
        return None
    import jax
    from jax.sharding import Mesh

    devs = tuple(jax.local_devices()[:n])
    key = (n, tuple(d.id for d in devs))
    with _cfg_lock:
        m = _mesh_cache.get(key)
        if m is None:
            m = Mesh(np.array(devs), (SHARD_AXIS,))
            _mesh_cache[key] = m
    return m


def query_mesh(want: bool = True):
    """The mesh one query's fused dispatches should run under: the
    active mesh, or None for the ``?nomesh=1`` escape.  NOT counted
    here — a single request consults this at several fused call sites
    (staging, per-shard-group batch fns), so the executor counts one
    ``mesh.fallbacks`` per executed request instead
    (``note_fallback``)."""
    if not want:
        return None
    return active_mesh()


def note_fallback() -> None:
    """One ?nomesh=1 request executed while the mesh was active — the
    fallback evidence operators read off /debug/mesh.  Called once
    per request (Executor.execute), never per fused call site."""
    if active():
        bump("mesh.fallbacks")


def placement_token(use_mesh: bool = True):
    """The placement flavor joined into stack-cache invalidation
    tuples: a [mesh] toggle or axis resize must MISS and re-place, not
    serve a stack laid out for the previous config."""
    if not use_mesh:
        return "dev"
    n = axis_size()
    return ("mesh", n) if n > 1 else "dev"


def pad_axis(use_mesh: bool = True) -> int:
    """The multiple the shard axis pads to under the given flavor —
    the mesh size (blocks must split evenly across devices), or 1 on
    the single-device path (no padding; the exact pre-mesh shapes)."""
    return axis_size() if use_mesh else 1


def pad_domain(n: int) -> int:
    """Container gather-domain padding: the next power of two (the
    O(log) lowered-shape discipline, pilosa-lint P4 — the shared
    ``containers._pow2`` helper, not a fourth copy) rounded up to a
    mesh-axis multiple so the domain shards evenly.  Axis sizes are
    nearly always powers of two, in which case this IS the pow2."""
    from pilosa_tpu.ops.containers import _pow2

    p = _pow2(max(1, n))
    a = axis_size()
    if a > 1 and p % a:
        p = ((p + a - 1) // a) * a
    return p


# --------------------------------------------------------------- placement


def shard_spec(ndim: int, shard_dim: int):
    """PartitionSpec placing ``shard_dim`` on the mesh axis."""
    from jax.sharding import PartitionSpec as P

    dims: list = [None] * ndim
    dims[shard_dim] = SHARD_AXIS
    return P(*dims)


def place_stack(stack: np.ndarray, label: str = "field.stack",
                mesh_label: str = "field.shard_stack"):
    """Place a host [shards, ...] array sharded over the active mesh
    (axis 0 = the shard axis), or as a plain uncommitted single-device
    put when the mesh is off (the pre-mesh placement — uncommitted so
    it composes with any committed operand in downstream jit calls).
    The caller pads axis 0 to a mesh-size multiple (``pad_axis``);
    transfer metering rides devobs under ``mesh_label``/``label`` for
    the sharded/single-device flavors like every other placement."""
    import jax
    from jax.sharding import NamedSharding

    m = active_mesh()
    if m is None:
        from pilosa_tpu.ops import bitmap as bm

        return bm.chunked_device_put(stack, label=label)
    from pilosa_tpu import devobs

    devobs.note_transfer(stack.nbytes, m.size, mesh_label)
    bump("mesh.placements")
    bump("mesh.placed_bytes", stack.nbytes)
    return jax.device_put(stack, NamedSharding(m, shard_spec(stack.ndim, 0)))


def place_replicated(arr, mesh=None, label: str = "field.containers"):
    """Place an array replicated on every mesh device (container word
    pools: gather indices address arbitrary pool rows, so the pool
    must be whole everywhere — the domain axis shards instead)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    m = mesh if mesh is not None else active_mesh()
    if m is None:
        from pilosa_tpu.ops import bitmap as bm

        return bm.chunked_device_put(arr, label=label)
    from pilosa_tpu import devobs

    devobs.note_transfer(arr.nbytes * m.size, m.size, label)
    bump("mesh.placements")
    bump("mesh.placed_bytes", arr.nbytes * m.size)
    return jax.device_put(arr, NamedSharding(m, P()))


def ensure_placed(arr, mesh, shard_dim: int):
    """Commit one operand to the mesh sharding a shard_map program
    requires.  jit does NOT reshard committed inputs across device
    sets (it raises), so the mesh route re-places every operand; when
    the sharding already matches this is a ~15 ns no-op, and when a
    leaf arrived single-device (a cold cache filled under ?nomesh, a
    test's monkeypatched placement) it is one explicit transfer
    instead of an error."""
    import jax
    from jax.sharding import NamedSharding

    return jax.device_put(
        arr, NamedSharding(mesh, shard_spec(arr.ndim, shard_dim)))


def ensure_replicated(arr, mesh):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.device_put(arr, NamedSharding(mesh, P()))


def shardable(mesh, n_rows: int) -> bool:
    """True when a shard-axis length splits evenly over the mesh —
    the precondition of every shard_map route; staging pads to make
    it so, and anything else (a stale memo from an axis resize) falls
    back to the single-device program rather than erroring."""
    return mesh is not None and n_rows % mesh.size == 0


def shard_plan(n_shards: int) -> list[dict]:
    """The per-device shard plan for an ``n_shards``-wide query: which
    padded shard-axis rows (and so which shards) each mesh device
    owns.  NamedSharding partitions axis 0 into equal contiguous
    blocks, so the plan is exactly row ``i`` -> device ``i // block``
    (the /debug/mesh surface; residency follows the same split)."""
    m = active_mesh()
    if m is None:
        return []
    a = m.size
    padded = ((n_shards + a - 1) // a) * a
    block = padded // a
    out = []
    for i, dev in enumerate(m.devices.flat):
        lo, hi = i * block, (i + 1) * block
        out.append({
            "device": dev.id,
            "platform": dev.platform,
            "rows": [lo, hi],
            "shards": [lo, min(hi, n_shards)] if lo < n_shards else [],
        })
    return out


# ------------------------------------------------------------ launch order

#: Serializes mesh-program dispatches process-wide.  A multi-device
#: (collective-carrying) computation enqueues work on EVERY mesh
#: device; two such computations dispatched concurrently from
#: different host threads can interleave their per-device enqueues in
#: different orders and deadlock the backend waiting on each other's
#: collectives — the standard multi-threaded-collectives hazard
#: (observed as a hard wedge on the multi-CPU-device test platform:
#: three reader threads inside the same gather program, none
#: progressing).  Holding this lock across the DISPATCH keeps the
#: per-device enqueue order globally consistent; execution itself
#: still pipelines (the dispatch returns async arrays), and
#: single-device programs never take it.
_launch_lock = threading.Lock()


def launch_lock() -> threading.Lock:
    """The process-wide mesh dispatch lock — every shard_map program
    dispatch (ops/expr, ops/tape mesh routes) runs under it."""
    return _launch_lock


# ---------------------------------------------------------------- counters

_lock = threading.Lock()
_counters = {
    "mesh.launches": 0,     # shard_map program dispatches (expr/tape/
                            # container routes combined)
    "mesh.queries": 0,      # queries those launches served (a coalesced
                            # megabatch counts each member)
    "mesh.fallbacks": 0,    # ?nomesh=1 requests while the mesh was active
    "mesh.placements": 0,   # operand placements onto the mesh
    "mesh.placed_bytes": 0,  # bytes those placements moved (replicated
                             # pools count once per device)
}


def bump(name: str, value: int = 1) -> None:
    with _lock:
        _counters[name] += value


def counters() -> dict[str, int]:
    with _lock:
        return dict(_counters)


def reset_counters() -> None:
    with _lock:
        for k in _counters:
            _counters[k] = 0


def note_launch(queries: int = 1) -> None:
    """One shard_map dispatch serving ``queries`` queries."""
    with _lock:
        _counters["mesh.launches"] += 1
        _counters["mesh.queries"] += queries


def publish_gauges(stats: Any) -> None:
    """Push the mesh.* family into a stats registry at scrape time —
    cumulative counters as gauges (the tape/container family rule),
    plus the axis layout in force."""
    for name, value in counters().items():
        stats.gauge(name, value)
    stats.gauge("mesh.devices", axis_size())
    stats.gauge("mesh.active", 1 if active() else 0)


def debug(n_shards: int | None = None) -> dict[str, Any]:
    """The GET /debug/mesh document: config in force, the resolved
    axis layout (devices joined to the shard axis), the per-device
    shard plan for an ``n_shards``-wide query (the widest index, when
    the handler knows it), and the mesh.* counters."""
    import jax

    m = active_mesh()
    try:
        n_local = len(jax.local_devices())
    except Exception:
        n_local = 0
    out: dict[str, Any] = {
        "enabled": _cfg.enabled,
        "axisSize": _cfg.axis_size,
        "active": m is not None,
        "axis": SHARD_AXIS,
        "localDevices": n_local,
        "devices": ([] if m is None else
                    [{"id": d.id, "platform": d.platform,
                      "kind": getattr(d, "device_kind", "")}
                     for d in m.devices.flat]),
        "counters": counters(),
    }
    if n_shards:
        out["plan"] = shard_plan(n_shards)
    return out
