"""Cross-query micro-batched dispatch: concurrent count-style queries
share one device launch.

The Count/Intersect hot path is dispatch-bound on a real chip behind an
RPC boundary (VERDICT round 5: 0.555 ms/query against a 20 us
trivial-dispatch floor, bw_util 0.148), and `bench.py`'s batched engine
proves one fused B=32 launch recovers the headroom.  This module is that
engine made product code — the serving-side batching lever TPU inference
stacks pull (Ragged Paged Attention, arxiv 2604.15464) applied to our
map-reduce-over-shards execution model (DrJAX, arxiv 2403.07128;
reference executor.go:2455 scatter-gather).

Mechanics
---------
Fused-eligible `Count(tree)` queries stage their operands on the calling
thread (`Executor._fused_expr`: canonical tree SHAPE + leaf stacks),
then meet in a bucket.  The first arrival becomes the bucket's LEADER
and waits up to ``window_s`` for followers; hitting ``max_batch`` seals
the bucket early.  The leader runs ONE launch for the sealed bucket and
scatters the per-query count rows back to every waiter's future.

Bucketing is two-tier:

- **Ragged (default)**: the query's tree compiles to an op-tape
  (ops/tape.py) and the bucket keys on the tape's SIZE CLASS (pow2
  tape length x pow2 leaf slots) plus the leaf stack shape — so
  STRUCTURALLY DIFFERENT trees share a window and a launch, the fix
  for mixed dashboard traffic that mostly missed the same-shape
  window and paid per-query dispatch.  At flush, a bucket whose live
  members all share one exact shape takes the same-shape fast path
  below (the specialized fused program, zero interpreter overhead);
  a heterogeneous bucket executes as one tape-interpreter launch.
- **Per-shape fallback**: with ``[ragged]`` disabled — or for a query
  whose tape exceeds the configured caps (``max-tape``/``max-leaves``)
  or carries a structurally ineligible node (Shift) — the bucket keys
  on ``(index, shape, shards)`` exactly as before, merging only
  identical-shape queries through the fused program.  The ragged
  engine can therefore be disabled in production with no behavior
  change (regression-pinned in tests/test_tape.py).

Same ops, same integer arithmetic on both paths — results are
bit-exact against the unbatched path; a batch of one takes the
identical single-query program (passthrough).

Enablement: OFF in host mode (single CPU device — dispatch is a Python
call there, batching buys nothing and the window would only add
latency); ON by default when an accelerator is attached.  The server
knobs live under ``[coalescer]`` and ``[ragged]``
(docs/configuration.md).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future

import numpy as np

from pilosa_tpu import observe as _observe
from pilosa_tpu import perfobs as _perfobs
from pilosa_tpu import stats as _stats
from pilosa_tpu import tracing
from pilosa_tpu.ops import containers as _containers
from pilosa_tpu.ops import tape as _tape
from pilosa_tpu.serve.deadline import DeadlineExceededError


def resolve_enabled(mode) -> bool:
    """``auto`` (accelerator-only) | true | false — TOML booleans and
    env strings both accepted.  Anything else is a configuration error
    and raises: a typo like ``enabled = "ture"`` silently falling back
    to auto would invert the operator's explicit intent."""
    if isinstance(mode, bool):
        return mode
    s = str(mode).strip().lower()
    if s in ("1", "true", "yes", "on"):
        return True
    if s in ("0", "false", "no", "off"):
        return False
    if s != "auto":
        raise ValueError(
            f"coalescer.enabled must be auto/true/false, got {mode!r}")
    from pilosa_tpu.ops import bitmap as bm

    return not bm.host_mode()


class _Bucket:
    __slots__ = ("items", "full", "sealed",
                 "n_final", "shapes_final", "tape_final", "vm_final",
                 "flush_t0", "launch_ns", "engine", "would_choose",
                 "flush_trace")

    def __init__(self):
        # _Entry per enqueued query
        self.items: list[_Entry] = []
        self.full = threading.Event()
        self.sealed = False
        # flight-recorder breakdown, written by the leader BEFORE the
        # futures resolve (so every waiter may read them after
        # fut.result() without a lock): final batch occupancy, distinct
        # shape count, whether the tape interpreter ran, whether the
        # bitmap VM ran, flush start (perf_counter_ns), and
        # device-launch duration
        self.n_final = 0
        self.shapes_final = 0
        self.tape_final = False
        self.vm_final = False
        self.flush_t0 = 0
        self.launch_ns = 0
        # the canonical perfobs engine the flush ran, and the shadow
        # cost model's verdict when it disagreed — followers stamp both
        # onto their own flight records (the ops-layer sample only sees
        # the leader's thread)
        self.engine: str | None = None
        self.would_choose: str | None = None
        # the LEADER's trace id at flush: batchmates inherit the
        # batch's launch span — a follower's /debug/trace tree can
        # point at the trace that actually owns the shared launch
        self.flush_trace: str | None = None


class _Entry:
    """One staged query waiting in a bucket.  ``tape`` is None on the
    per-shape fallback path (ragged off / oversize / Shift); ``mesh``
    is the device mesh this query's launch must run under (None = the
    pre-mesh single-device programs — ?nomesh=1 / [mesh] off).  The
    bucket key carries the mesh identity, so queries on different
    placement flavors never share a launch.  ``vm`` is the query's
    compressed VM staging (ops/containers.VMStage) when the bitmap VM
    routes it — VM entries carry no dense leaf stacks at all."""

    __slots__ = ("shape", "leaves", "tape", "fut", "deadline", "mesh",
                 "vm")

    def __init__(self, shape, leaves, tape, fut, deadline, mesh=None,
                 vm=None):
        self.shape = shape
        self.leaves = leaves
        self.tape = tape
        self.fut = fut
        self.deadline = deadline
        self.mesh = mesh
        self.vm = vm


class Coalescer:
    """One per executor.  Thread-safe; queries block at most
    ``window_s`` beyond their own execution time."""

    def __init__(self, window_s: float = 0.002, max_batch: int = 32,
                 enabled="auto", stats=None, ragged: bool = True,
                 max_tape: int = _tape.DEFAULT_MAX_TAPE,
                 max_leaves: int = _tape.DEFAULT_MAX_LEAVES,
                 vm: bool = True,
                 vm_min_domain: int = _containers.VM_MIN_DOMAIN,
                 vm_max_prefetch: int = _containers.VM_MAX_PREFETCH):
        self.window_s = window_s
        self.max_batch = max_batch
        self.enabled = resolve_enabled(enabled)
        self.ragged = bool(ragged)
        self.max_tape = max_tape
        self.max_leaves = max_leaves
        # the Pallas bitmap VM ([vm] config): heterogeneous ragged
        # buckets whose every leaf stages compressed execute as ONE
        # scalar-prefetch kernel over the pooled containers — rides
        # the ragged engine, so [ragged] off disables it too
        self.vm = bool(vm)
        self.vm_min_domain = int(vm_min_domain)
        self.vm_max_prefetch = int(vm_max_prefetch)
        self.stats = stats if stats is not None else _stats.NOP
        from pilosa_tpu import lockcheck

        self._lock = lockcheck.lock("coalescer")
        self._pending: dict[tuple, _Bucket] = {}
        # (shape, n_leaves) -> (Tape|None, fallback-counter-name|None):
        # shapes are canonical/hashable and few, so compile each once
        # instead of re-walking the tree (and re-raising TapeError for
        # Shift shapes) on every staged query of the serving hot path.
        # Unlocked by design: a racing duplicate compile is wasted
        # work, never a wrong entry; cleared wholesale on overflow.
        self._tape_memo: dict[tuple, tuple] = {}

    # ------------------------------------------------------------- entry

    def eligible(self, opt) -> bool:
        """Gate consulted by the executor's fused Count path — the
        caller has already established fusion eligibility and
        single-node execution.  A query whose remaining deadline is
        within two batching windows bypasses the coalescer entirely:
        never hold a query past its budget just to share a launch."""
        if not (self.enabled and (opt is None or opt.coalesce)):
            return False
        dl = None if opt is None else getattr(opt, "deadline", None)
        return dl is None or dl.remaining() > 2 * self.window_s

    def _tape_for(self, shape, n_leaves):
        """Memoized compile: Tape within the caps, or None (with the
        per-QUERY fallback counter bumped — the memo dedupes the tree
        walk, never the accounting)."""
        mkey = (shape, n_leaves)
        hit = self._tape_memo.get(mkey)
        if hit is None:
            try:
                tp = _tape.compile_shape(shape, n_leaves,
                                         self.max_tape)
                reason = None
                if n_leaves > self.max_leaves:
                    tp, reason = None, "tape.oversize_fallbacks"
            except _tape.TapeError as e:
                tp = None
                reason = ("tape.oversize_fallbacks"
                          if "exceeds cap" in str(e)
                          else "tape.unsupported")
            if len(self._tape_memo) >= 4096:
                self._tape_memo.clear()
            self._tape_memo[mkey] = hit = (tp, reason)
        tp, reason = hit
        if reason is not None:
            _tape.bump(reason)
        return tp

    def _bucket_key(self, idx, shape, shards, leaves, mesh=None):
        """(key, tape) for one staged query.  Ragged: tape compiles
        within the caps -> key on the size class + leaf stack shape,
        so heterogeneous trees of similar size meet in one bucket
        (distinct indexes included — the launch is index-agnostic;
        each waiter folds its own result).  Fallback: the exact
        per-shape key, the pre-ragged behavior.  The mesh identity
        joins both keys: a ?nomesh=1 query must not share a launch
        with mesh-routed batchmates (different compiled programs)."""
        if self.ragged:
            tp = self._tape_for(shape, len(leaves))
            if tp is not None:
                tb, lb = _tape.size_class(len(tp.instrs), len(leaves))
                return (("ragged", tuple(leaves[0].shape), tb, lb,
                         mesh), tp)
        return (idx.name, shape, shards, mesh), None

    def count(self, executor, idx, child, shards: tuple[int, ...],
              deadline=None, cache_fill=None,
              use_delta: bool = True, mesh=None,
              tenant: str | None = None,
              use_vm: bool = True) -> int:
        """One Count(tree) query through the batching window -> total.
        Staging runs on the CALLER's thread (fragment locks, and a
        staging error belongs to this query alone).

        ``cache_fill`` is the executor's result-cache probe triple
        ``(cache, key, gens)`` for THIS query — the executor already
        probed (a hit never reaches the window), so a flushed batch
        fills the cache for every member: each waiter stores its own
        total under its own key, stamped with the generations captured
        before its leaves were staged.  Entries dropped from the batch
        (deadline death, flush failure) raise out of ``fut.result()``
        and never fill.

        ``tenant`` is the query's tenant id ([tenants] isolation):
        tenants SHARE launches by design — batching across tenants is
        the whole point of the window — but each member's cache fill
        below charges its own tenant's soft budget.

        ``use_delta=False`` is the ?nodelta=1 escape, forwarded to
        staging.  Bucket keys stay delta-aware for free: a pending
        ingest delta puts ``dfuse`` nodes in the canonical SHAPE —
        which the tape compiler lowers to two extra instructions, so a
        delta-carrying query lands in the size class its overlay
        actually costs — and a ?nodelta=1 query (which compacts up
        front and stages plain leaves) batches with a delta-reading
        one only when the programs are identical anyway."""
        vmstage = None
        if self.vm and self.ragged and use_vm and mesh is None:
            # the bitmap VM: stage compressed (directories + local
            # gather rows, NO dense stacks) and key on the tape size
            # class alone — domain widths re-pad to the bucket max at
            # flush, so 16 structurally distinct sparse queries still
            # meet in ONE bucket and ONE kernel.  mesh is None only:
            # the VM is a single-device kernel; mesh-routed queries
            # keep the shard_map interpreter.  Any decline (dense/hot
            # leaf, ineligible tree, oversize) falls through to the
            # existing ragged/fused staging below, all-or-nothing.
            vmstage = _containers.stage_vm(
                idx, child, shards, use_delta=use_delta,
                max_tape=self.max_tape, max_leaves=self.max_leaves,
                min_domain=self.vm_min_domain,
                max_prefetch=self.vm_max_prefetch)
            if vmstage is None:
                _tape.bump("vm.fallbacks")
        elif self.vm and self.ragged and use_vm:
            # mesh-routed query: informational reason cell ONLY — the
            # shard_map interpreter is a route, not a degradation, so
            # the central vm.fallbacks total stays untouched
            _tape.bump("vm.fallbacks.mesh_active")
        if vmstage is not None:
            tb, lb = _tape.size_class(len(vmstage.tape.instrs),
                                      len(vmstage.leaves))
            key = ("vm", tb, lb)
            entry = _Entry(vmstage.shape, (), vmstage.tape, Future(),
                           deadline, mesh=None, vm=vmstage)
        else:
            shape, leaves = executor._fused_expr(idx, child, shards,
                                                 use_delta=use_delta)
            key, tp = self._bucket_key(idx, shape, shards, leaves,
                                       mesh=mesh)
            entry = _Entry(shape, leaves, tp, Future(), deadline,
                           mesh=mesh)
        t0 = time.perf_counter_ns()
        with self._lock:
            bucket = self._pending.get(key)
            leader = bucket is None
            if leader:
                bucket = _Bucket()
                self._pending[key] = bucket
            bucket.items.append(entry)
            if len(bucket.items) >= self.max_batch:
                bucket.sealed = True
                del self._pending[key]
                bucket.full.set()
        if leader:
            bucket.full.wait(self.window_s)
            with self._lock:
                if not bucket.sealed:
                    bucket.sealed = True
                    del self._pending[key]
            self._flush(bucket)
        counts = entry.fut.result()
        self.stats.timing("coalescer.query_ns",
                          time.perf_counter_ns() - t0)
        rec = _observe.current()
        if rec is not None:
            # bucket fields are final once fut resolved (leader writes
            # them before scattering results).  The batch's shared
            # launch ticks the LEADER's deviceLaunches only (the hook
            # is thread-local and honest — a follower never dispatched
            # anything); followers carry the launch evidence here, in
            # the batch context, with ``leader`` saying which record
            # owns the tick.
            rec.note_path("coalesced")
            if bucket.engine is not None:
                rec.note_engine(bucket.engine)
            if bucket.would_choose is not None:
                rec.would_choose = bucket.would_choose
            rec.coalesce = {
                "batch": bucket.n_final,
                "shapes": bucket.shapes_final,
                "tape": bucket.tape_final,
                "vm": bucket.vm_final,
                "queue_wait_ns": max(0, bucket.flush_t0 - t0),
                "launch_ns": bucket.launch_ns,
                "leader": leader,
            }
            if bucket.flush_trace and not leader:
                # a follower's record names the batch leader's trace —
                # the span that owns the shared device launch
                rec.coalesce["launch_trace"] = bucket.flush_trace
        arr = np.asarray(counts, dtype=np.int64)
        if entry.vm is not None:
            # VM results are per-domain-slot counts over the bucket's
            # padded domain — pad slots gather the megapool zero row
            # and contribute 0, and the domain already concatenated
            # the per-shard walks, so the total sums ALL slots (there
            # is no shard-row alignment to trim)
            total = int(arr.sum())
        else:
            # leaf stacks are padded to the device multiple — sum only
            # the live shard rows, in Python ints (int32 could wrap)
            total = int(arr[:len(shards)].sum())
        if cache_fill is not None:
            rc, key, gens = cache_fill
            rc.put(key, gens, total, 32, tenant=tenant)
        return total

    # ------------------------------------------------------------- flush

    def _flush(self, bucket: _Bucket) -> None:
        """Leader-side: ONE launch for the sealed bucket, results
        scattered to every waiter.  Appends are impossible once sealed
        (sealing happens under the same lock that guards appends).
        EVERYTHING here runs inside the try: any failure — including
        stats/tracing backends or the ops import — must resolve every
        waiter's future, or followers would block forever."""
        # deadline-aware launch: entries whose budget died while the
        # window was open are dropped from the batch BEFORE launch —
        # their futures resolve to DeadlineExceededError, and their
        # batchmates' results are unaffected (the stack simply omits
        # the expired rows)
        live: list[_Entry] = []
        expired: list[_Entry] = []
        for it in bucket.items:
            dl = it.deadline
            (expired if dl is not None and dl.expired()
             else live).append(it)
        for it in expired:
            it.fut.set_exception(DeadlineExceededError(
                "deadline expired in the coalescer window"))
        n = len(live)
        bucket.n_final = n
        shape_groups: dict = {}
        for it in live:
            shape_groups[it.shape] = shape_groups.get(it.shape, 0) + 1
        bucket.shapes_final = len(shape_groups)
        bucket.flush_t0 = time.perf_counter_ns()
        if expired:
            try:
                self.stats.count("coalescer.deadline_dropped",
                                 len(expired))
            except Exception:  # noqa: BLE001 — telemetry must never
                pass  # strand the live waiters below
        if n == 0:
            return
        try:
            from pilosa_tpu.ops import expr

            # heterogeneity accounting (the before/after evidence for
            # the ragged engine): a query whose flushed batch held no
            # same-shape partner is a shape MISS — with ragged off it
            # flushed alone; with ragged on it still shared the launch,
            # and the counter measures how much structural diversity
            # the traffic carries either way
            misses = sum(1 for c in shape_groups.values() if c == 1)
            if misses:
                # cumulative module counter, exposed as a gauge at
                # scrape time (tape.publish_gauges) — never ALSO
                # pushed as a count, which would double-count (the
                # ingest.*/cache.* family rule)
                _tape.bump("coalescer.shape_misses", misses)
            if bucket.shapes_final > 1:
                _tape.bump("coalescer.shape_flushes")
            self.stats.count("coalescer.dispatches", 1)
            self.stats.histogram("coalescer.batch_occupancy", n)
            self.stats.histogram("coalescer.shape_distinct",
                                 bucket.shapes_final)
            with tracing.start_span("coalescer.flush") as span:
                span.set_tag("batch", n)
                span.set_tag("shapes", bucket.shapes_final)
                bucket.flush_trace = tracing.active_trace_id()
                t_launch = time.perf_counter_ns()
                from pilosa_tpu.runtime import residency as _residency

                # the batch's workload signature for the engine
                # observatory: dense-equivalent uint32 words (the
                # size-class key every engine's cost-table cell shares)
                # and bytes-touched / dense-equivalent sparsity — the
                # perfobs.context scope threads both to the ops-layer
                # launch sample, and the shadow consult below looks up
                # candidate engines at the same coordinates
                sig_work = sum(
                    int(lv.size) for it in live for lv in it.leaves)
                sig_sparsity = 1.0
                if live[0].vm is not None:
                    # bitmap-VM bucket (every entry staged compressed
                    # — the key's "vm" leader guarantees it): the
                    # distinct leaves concatenate into ONE megapool,
                    # each entry's local gather rows globalize against
                    # it (re-padded to the bucket-wide domain width
                    # with the canonical zero row), and the whole
                    # heterogeneous batch executes as ONE
                    # scalar-prefetch kernel that never materializes a
                    # dense register file (ops/tape.execute_vm ->
                    # ops/pallas_kernels.vm_counts)
                    bucket.tape_final = True
                    bucket.vm_final = True
                    span.set_tag("vm", True)
                    tb, lb = _tape.size_class(
                        max(len(it.tape.instrs) for it in live),
                        max(len(it.vm.leaves) for it in live))
                    D = max(it.vm.pad for it in live)
                    pool, bases, zero = _containers.megapool(
                        [lf for it in live for lf in it.vm.leaves])
                    vbatch = []
                    for it in live:
                        rows = []
                        for lf, ix in zip(it.vm.leaves, it.vm.idxs):
                            g = np.full(D, zero, dtype=np.int32)
                            if isinstance(ix, tuple):
                                # kind-split staging: combine the
                                # per-kind rows into the bundle's
                                # virtual dense row space ([0, Rb)
                                # bitmap, then arrays, then runs —
                                # containers.MegaPools); kv 0/1 both
                                # route through the bitmap base (an
                                # absent lane's ib is the leaf's zero
                                # row)
                                kv, ib, ia, ir = ix
                                bb, ab, rb = bases[lf.uid]
                                g[:len(ib)] = np.where(
                                    kv == 2, ab + ia,
                                    np.where(kv == 3, rb + ir,
                                             bb + ib)).astype(np.int32)
                            else:
                                base = bases[lf.uid]
                                if isinstance(base, tuple):
                                    base = base[0]  # legacy leaf in a
                                    # kinds megapool: bitmap rows only
                                g[:len(ix)] = base + ix
                            rows.append(g)
                        vbatch.append((it.tape, rows))
                    # domain slots holding a real container vs the
                    # padded directory capacity: the data sparsity the
                    # compressed engine exploits
                    cap = sum(len(it.vm.leaves) for it in live) * D
                    real = sum(len(ix[1] if isinstance(ix, tuple)
                                   else ix)
                               for it in live for ix in it.vm.idxs)
                    sig_work = cap * int(pool.shape[-1])
                    sig_sparsity = real / cap if cap else 1.0
                    bucket.engine = "vm"
                    with _perfobs.context(sparsity=sig_sparsity,
                                          work=sig_work):
                        results = _residency.run_with_oom_retry(
                            lambda: _tape.execute_vm(
                                vbatch, pool, zero, tape_len=tb,
                                slots=lb,
                                max_prefetch=self.vm_max_prefetch))
                elif n == 1:
                    # single-query passthrough: the identical program
                    # the un-coalesced path would run
                    bucket.engine = ("mesh" if live[0].mesh is not None
                                     else "dense")
                    with _perfobs.context(work=sig_work):
                        results = _residency.run_with_oom_retry(
                            lambda: [expr.evaluate(live[0].shape,
                                                   live[0].leaves,
                                                   counts=True,
                                                   mesh=live[0].mesh)])
                elif bucket.shapes_final == 1:
                    # same-shape fast path: the specialized fused
                    # program over stacked operands, exactly the
                    # pre-ragged engine (and what a ragged bucket that
                    # happened to fill homogeneously should run — the
                    # interpreter buys nothing over a specialized
                    # program)
                    shape = live[0].shape
                    stacked = tuple(
                        _stack([it.leaves[j] for it in live])
                        for j in range(len(live[0].leaves)))
                    # device batches pad to the next power of two: the
                    # jitted program re-lowers per INPUT shape, so
                    # free-running occupancies (2, 3, 5, ...) each pay
                    # a fresh XLA compile in the serving path — under
                    # sustained ingest the misses arrive at arbitrary
                    # batch sizes and the compiles convoy every other
                    # query in the process.  Bucketing holds the
                    # variant count at log2(max_batch); the zero pad
                    # rows count to zero and are never scattered back.
                    # Host stacks skip it (the host engine never jits).
                    pad = _pow2(n) - n
                    if pad and not isinstance(stacked[0], np.ndarray):
                        stacked = tuple(_pad_batch(s, pad)
                                        for s in stacked)
                    bucket.engine = ("mesh" if live[0].mesh is not None
                                     else "dense")
                    with _perfobs.context(work=sig_work):
                        counts = np.asarray(
                            _residency.run_with_oom_retry(
                                lambda: expr.evaluate(
                                    shape, stacked, counts=True,
                                    mesh=live[0].mesh,
                                    # live occupancy, not the pow2-
                                    # padded batch rows, feeds the
                                    # mesh.queries counter
                                    mesh_queries=n)),
                            dtype=np.int64)
                    results = [counts[b] for b in range(n)]
                else:
                    # heterogeneous bucket: the whole ragged batch as
                    # ONE tape-interpreter launch (ops/tape.py); the
                    # bucket key guarantees every member's tape fits
                    # the (tape_len, slots) size class and every leaf
                    # stack shares one shape
                    bucket.tape_final = True
                    span.set_tag("tape", True)
                    tb, lb = _tape.size_class(
                        max(len(it.tape.instrs) for it in live),
                        max(it.tape.n_leaves for it in live))
                    bucket.engine = ("mesh" if live[0].mesh is not None
                                     else "tape")
                    with _perfobs.context(work=sig_work):
                        results = _residency.run_with_oom_retry(
                            lambda: _tape.execute(
                                [(it.tape, it.leaves) for it in live],
                                counts=True, tape_len=tb, slots=lb,
                                mesh=live[0].mesh))
                bucket.launch_ns = time.perf_counter_ns() - t_launch
                self.stats.timing("coalescer.launch_ns",
                                  bucket.launch_ns)
                # SHADOW cost consult ([cost] shadow): would the table
                # have routed this batch to a different engine at the
                # same workload coordinates?  Verdict lands on the
                # flight records only — the launch above already ran
                # and is byte-identical either way
                bucket.would_choose = _perfobs.would_choose(
                    bucket.engine,
                    {e: (sig_work, sig_sparsity)
                     for e in ("dense", "tape", "vm", bucket.engine)})
        except BaseException as e:  # noqa: BLE001 — every waiter fails
            for it in live:
                it.fut.set_exception(e)
            return
        for it, row in zip(live, results):
            it.fut.set_result(row)


def _stack(arrs: list):
    """Stack one leaf slot across the batch -> [B, S, W].  numpy for
    host stacks; jnp on device (one gather launch per leaf slot,
    amortized over the B queries it serves)."""
    if all(isinstance(a, np.ndarray) for a in arrs):
        return np.stack(arrs)
    import jax.numpy as jnp

    return jnp.stack(arrs)


def _pow2(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


def _pad_batch(stack, pad: int):
    """Append ``pad`` zero rows along the batch dim (device stacks)."""
    import jax.numpy as jnp

    return jnp.concatenate(
        [stack, jnp.zeros((pad,) + stack.shape[1:], stack.dtype)])
