"""Cross-query micro-batched dispatch: concurrent count-style queries
share one device launch.

The Count/Intersect hot path is dispatch-bound on a real chip behind an
RPC boundary (VERDICT round 5: 0.555 ms/query against a 20 us
trivial-dispatch floor, bw_util 0.148), and `bench.py`'s batched engine
proves one fused B=32 launch recovers the headroom.  This module is that
engine made product code — the serving-side batching lever TPU inference
stacks pull (Ragged Paged Attention, arxiv 2604.15464) applied to our
map-reduce-over-shards execution model (DrJAX, arxiv 2403.07128;
reference executor.go:2455 scatter-gather).

Mechanics
---------
Fused-eligible `Count(tree)` queries stage their operands on the calling
thread (`Executor._fused_expr`: canonical tree SHAPE + leaf stacks),
then meet in a bucket keyed by ``(index, shape, shards)``.  The first
arrival becomes the bucket's LEADER and waits up to ``window_s`` for
followers; hitting ``max_batch`` seals the bucket early.  The leader
stacks each leaf slot across the batch ([B, S, W]), runs ops.expr's
compiled program ONCE (the count root reduces inside the same program),
and scatters the per-query count rows back to every waiter's future.
Same ops, same integer arithmetic — results are bit-exact against the
unbatched path; a batch of one takes the identical single-query program
(passthrough).

Keyed on shape, not query text: ``Count(Intersect(Row(f=3), Row(f=9)))``
and ``Count(Intersect(Row(f=7), Row(f=2)))`` coalesce (distinct leaf
VALUES, one compiled program); only structurally different trees (or
different shard sets) dispatch separately.

Enablement: OFF in host mode (single CPU device — dispatch is a Python
call there, batching buys nothing and the window would only add
latency); ON by default when an accelerator is attached.  The server
knobs live under ``[coalescer]`` (docs/configuration.md).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future

import numpy as np

from pilosa_tpu import observe as _observe
from pilosa_tpu import stats as _stats
from pilosa_tpu import tracing
from pilosa_tpu.serve.deadline import DeadlineExceededError


def resolve_enabled(mode) -> bool:
    """``auto`` (accelerator-only) | true | false — TOML booleans and
    env strings both accepted.  Anything else is a configuration error
    and raises: a typo like ``enabled = "ture"`` silently falling back
    to auto would invert the operator's explicit intent."""
    if isinstance(mode, bool):
        return mode
    s = str(mode).strip().lower()
    if s in ("1", "true", "yes", "on"):
        return True
    if s in ("0", "false", "no", "off"):
        return False
    if s != "auto":
        raise ValueError(
            f"coalescer.enabled must be auto/true/false, got {mode!r}")
    from pilosa_tpu.ops import bitmap as bm

    return not bm.host_mode()


class _Bucket:
    __slots__ = ("items", "full", "sealed",
                 "n_final", "flush_t0", "launch_ns")

    def __init__(self):
        # (leaves, future, deadline-or-None) per enqueued query
        self.items: list[tuple] = []
        self.full = threading.Event()
        self.sealed = False
        # flight-recorder breakdown, written by the leader BEFORE the
        # futures resolve (so every waiter may read them after
        # fut.result() without a lock): final batch occupancy, flush
        # start (perf_counter_ns), and device-launch duration
        self.n_final = 0
        self.flush_t0 = 0
        self.launch_ns = 0


class Coalescer:
    """One per executor.  Thread-safe; queries block at most
    ``window_s`` beyond their own execution time."""

    def __init__(self, window_s: float = 0.002, max_batch: int = 32,
                 enabled="auto", stats=None):
        self.window_s = window_s
        self.max_batch = max_batch
        self.enabled = resolve_enabled(enabled)
        self.stats = stats if stats is not None else _stats.NOP
        self._lock = threading.Lock()
        self._pending: dict[tuple, _Bucket] = {}

    # ------------------------------------------------------------- entry

    def eligible(self, opt) -> bool:
        """Gate consulted by the executor's fused Count path — the
        caller has already established fusion eligibility and
        single-node execution.  A query whose remaining deadline is
        within two batching windows bypasses the coalescer entirely:
        never hold a query past its budget just to share a launch."""
        if not (self.enabled and (opt is None or opt.coalesce)):
            return False
        dl = None if opt is None else getattr(opt, "deadline", None)
        return dl is None or dl.remaining() > 2 * self.window_s

    def count(self, executor, idx, child, shards: tuple[int, ...],
              deadline=None, cache_fill=None,
              use_delta: bool = True) -> int:
        """One Count(tree) query through the batching window -> total.
        Staging runs on the CALLER's thread (fragment locks, and a
        staging error belongs to this query alone).

        ``cache_fill`` is the executor's result-cache probe triple
        ``(cache, key, gens)`` for THIS query — the executor already
        probed (a hit never reaches the window), so a flushed batch
        fills the cache for every member: each waiter stores its own
        total under its own key, stamped with the generations captured
        before its leaves were staged.  Entries dropped from the batch
        (deadline death, flush failure) raise out of ``fut.result()``
        and never fill.

        ``use_delta=False`` is the ?nodelta=1 escape, forwarded to
        staging.  The bucket key stays delta-aware for free: a pending
        ingest delta puts ``dfuse`` nodes in the canonical SHAPE, so a
        delta-carrying query can only batch with queries fusing the
        same overlay structure — and a ?nodelta=1 query (which compacts
        up front and stages plain leaves) with a delta-reading one only
        when no delta is pending, where the programs are identical."""
        shape, leaves = executor._fused_expr(idx, child, shards,
                                             use_delta=use_delta)
        key = (idx.name, shape, shards)
        fut: Future = Future()
        t0 = time.perf_counter_ns()
        with self._lock:
            bucket = self._pending.get(key)
            leader = bucket is None
            if leader:
                bucket = _Bucket()
                self._pending[key] = bucket
            bucket.items.append((leaves, fut, deadline))
            if len(bucket.items) >= self.max_batch:
                bucket.sealed = True
                del self._pending[key]
                bucket.full.set()
        if leader:
            bucket.full.wait(self.window_s)
            with self._lock:
                if not bucket.sealed:
                    bucket.sealed = True
                    del self._pending[key]
            self._flush(shape, bucket)
        counts = fut.result()
        self.stats.timing("coalescer.query_ns",
                          time.perf_counter_ns() - t0)
        rec = _observe.current()
        if rec is not None:
            # bucket fields are final once fut resolved (leader writes
            # them before scattering results).  The batch's shared
            # launch ticks the LEADER's deviceLaunches only (the hook
            # is thread-local and honest — a follower never dispatched
            # anything); followers carry the launch evidence here, in
            # the batch context, with ``leader`` saying which record
            # owns the tick.
            rec.note_path("coalesced")
            rec.coalesce = {
                "batch": bucket.n_final,
                "queue_wait_ns": max(0, bucket.flush_t0 - t0),
                "launch_ns": bucket.launch_ns,
                "leader": leader,
            }
        # leaf stacks are padded to the device multiple — sum only the
        # live shard rows, in Python ints (int32 could wrap)
        total = int(np.asarray(counts, dtype=np.int64)[:len(shards)].sum())
        if cache_fill is not None:
            rc, key, gens = cache_fill
            rc.put(key, gens, total, 32)
        return total

    # ------------------------------------------------------------- flush

    def _flush(self, shape, bucket: _Bucket) -> None:
        """Leader-side: ONE launch for the sealed bucket, results
        scattered to every waiter.  Appends are impossible once sealed
        (sealing happens under the same lock that guards appends).
        EVERYTHING here runs inside the try: any failure — including
        stats/tracing backends or the ops import — must resolve every
        waiter's future, or followers would block forever."""
        # deadline-aware launch: entries whose budget died while the
        # window was open are dropped from the batch BEFORE launch —
        # their futures resolve to DeadlineExceededError, and their
        # batchmates' results are unaffected (the stack simply omits
        # the expired rows)
        live: list[tuple] = []
        expired: list = []
        for it in bucket.items:
            dl = it[2]
            (expired if dl is not None and dl.expired()
             else live).append(it)
        for it in expired:
            it[1].set_exception(DeadlineExceededError(
                "deadline expired in the coalescer window"))
        n = len(live)
        bucket.n_final = n
        bucket.flush_t0 = time.perf_counter_ns()
        if expired:
            try:
                self.stats.count("coalescer.deadline_dropped",
                                 len(expired))
            except Exception:  # noqa: BLE001 — telemetry must never
                pass  # strand the live waiters below
        if n == 0:
            return
        try:
            from pilosa_tpu.ops import expr

            self.stats.count("coalescer.dispatches", 1)
            self.stats.histogram("coalescer.batch_occupancy", n)
            with tracing.start_span("coalescer.flush") as span:
                span.set_tag("batch", n)
                t_launch = time.perf_counter_ns()
                if n == 1:
                    # single-query passthrough: the identical program
                    # the un-coalesced path would run
                    results = [expr.evaluate(shape, live[0][0],
                                             counts=True)]
                else:
                    stacked = tuple(
                        _stack([it[0][j] for it in live])
                        for j in range(len(live[0][0])))
                    # device batches pad to the next power of two: the
                    # jitted program re-lowers per INPUT shape, so
                    # free-running occupancies (2, 3, 5, ...) each pay
                    # a fresh XLA compile in the serving path — under
                    # sustained ingest the misses arrive at arbitrary
                    # batch sizes and the compiles convoy every other
                    # query in the process.  Bucketing holds the
                    # variant count at log2(max_batch); the zero pad
                    # rows count to zero and are never scattered back.
                    # Host stacks skip it (the host engine never jits).
                    pad = _pow2(n) - n
                    if pad and not isinstance(stacked[0], np.ndarray):
                        stacked = tuple(_pad_batch(s, pad)
                                        for s in stacked)
                    counts = np.asarray(
                        expr.evaluate(shape, stacked, counts=True),
                        dtype=np.int64)
                    results = [counts[b] for b in range(n)]
                bucket.launch_ns = time.perf_counter_ns() - t_launch
                self.stats.timing("coalescer.launch_ns",
                                  bucket.launch_ns)
        except BaseException as e:  # noqa: BLE001 — every waiter fails
            for it in live:
                it[1].set_exception(e)
            return
        for it, row in zip(live, results):
            it[1].set_result(row)


def _stack(arrs: list):
    """Stack one leaf slot across the batch -> [B, S, W].  numpy for
    host stacks; jnp on device (one gather launch per leaf slot,
    amortized over the B queries it serves)."""
    if all(isinstance(a, np.ndarray) for a in arrs):
        return np.stack(arrs)
    import jax.numpy as jnp

    return jnp.stack(arrs)


def _pow2(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


def _pad_batch(stack, pad: int):
    """Append ``pad`` zero rows along the batch dim (device stacks)."""
    import jax.numpy as jnp

    return jnp.concatenate(
        [stack, jnp.zeros((pad,) + stack.shape[1:], stack.dtype)])
