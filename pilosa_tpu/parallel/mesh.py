"""Mesh-parallel query execution: shard fan-out as SPMD over a device mesh.

The TPU-native replacement for the reference's mapReduce HTTP
scatter-gather (executor.go:2455-2608): shards stack into dense tensors
sharded over a ``jax.sharding.Mesh`` axis, per-shard set algebra runs as
one fused XLA program on every device, and cross-shard reduction rides
ICI collectives (``psum`` for counts, bitwise-OR all-reduce for row
merges) instead of HTTP responses.  Multi-host scaling uses the same code
path: the mesh spans hosts and XLA routes collectives over ICI/DCN.

Key programs:
- count_intersect: Count(Intersect(Row, Row)) — the north-star op.
- bitmap_reduce: segment-wise OR/AND/XOR merge of per-shard bitmaps.
- topn_counts: phase-1 TopN per-row counts psum'd across shards; the
  phase-2 candidate re-count of the reference's protocol
  (executor.go:860-928) collapses into the same collective because counts
  are exact (no rank-cache approximation to reconcile).
- bsi_sum: per-plane popcounts psum'd across shards (GroupBy/Sum path).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exports shard_map at top level
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

SHARD_AXIS = "shards"


def device_mesh(n_devices: int | None = None, axis_name: str = SHARD_AXIS) -> Mesh:
    """A 1-D mesh over the shard axis.  The shard space is the only data
    dimension of a bitmap index (SURVEY.md §2.5: sharding is the
    reference's entire parallelism strategy), so a 1-D mesh is the whole
    layout; multi-host pods extend this axis across hosts."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devs)} available"
            )
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def local_device_mesh(axis_name: str = SHARD_AXIS) -> Mesh:
    """A 1-D mesh over THIS process's devices only — the per-node fused
    executor path in a multi-process deployment.  Per-node stacks hold
    node-local fragments, so placing them on the global mesh would both
    violate jax's same-value-everywhere rule for host arrays and imply
    collectives nobody else is entering; node-local work stays local,
    and only parallel/spmd.py plans span processes."""
    return Mesh(np.array(jax.local_devices()), (axis_name,))


def shard_stack(mesh: Mesh, stack: np.ndarray):
    """Place a [shards, ...] host array sharded over the mesh axis."""
    spec = P(SHARD_AXIS, *([None] * (stack.ndim - 1)))
    return jax.device_put(stack, NamedSharding(mesh, spec))


@functools.partial(jax.jit, static_argnums=(0,))
def _count_intersect(mesh, a, b):
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS, None), P(SHARD_AXIS, None)),
        out_specs=P(),
    )
    def step(a_blk, b_blk):
        part = jnp.sum(lax.population_count(a_blk & b_blk), dtype=jnp.int32)
        return lax.psum(part, SHARD_AXIS)

    return step(a, b)


def count_intersect(mesh: Mesh, a, b) -> int:
    """|A ∩ B| where A, B are [shards, words] stacks sharded over the mesh.
    AND + popcount fuse on-device; the only cross-device traffic is one
    scalar psum over ICI (vs the reference's per-node HTTP responses)."""
    return int(_count_intersect(mesh, a, b))


@functools.partial(jax.jit, static_argnums=(0, 1))
def _bitmap_reduce(mesh, op: str, stacks):
    reducer = {"or": jnp.bitwise_or, "and": jnp.bitwise_and, "xor": jnp.bitwise_xor}[op]

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS, None),) * len(stacks),
        out_specs=P(SHARD_AXIS, None),
    )
    def step(*blks):
        out = blks[0]
        for b in blks[1:]:
            out = reducer(out, b)
        return out

    return step(*stacks)


def bitmap_combine(mesh: Mesh, op: str, *stacks):
    """Elementwise combine of N sharded [shards, words] stacks, output
    stays sharded in place (no collective needed — set algebra is
    embarrassingly shard-parallel, SURVEY.md §2.5)."""
    return _bitmap_reduce(mesh, op, tuple(stacks))


@functools.partial(jax.jit, static_argnums=(0,))
def _topn_counts(mesh, matrix, filt):
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS, None, None), P(SHARD_AXIS, None)),
        out_specs=P(),
    )
    def step(mat_blk, filt_blk):
        masked = mat_blk & filt_blk[:, None, :]
        local = jnp.sum(
            lax.population_count(masked), axis=(0, 2), dtype=jnp.int32
        )
        return lax.psum(local, SHARD_AXIS)

    return step(matrix, filt)


def topn(mesh: Mesh, matrix, filt, n: int):
    """TopN over a [shards, rows, words] stack with a [shards, words]
    filter: per-row counts reduce with one psum; top-k runs replicated.
    Returns (row_slots, counts) as numpy."""
    counts = _topn_counts(mesh, matrix, filt)
    k = min(n, counts.shape[0]) if n else counts.shape[0]
    vals, idx = lax.top_k(counts, k)
    return np.asarray(idx), np.asarray(vals)


@functools.partial(jax.jit, static_argnums=(0,))
def _bsi_plane_counts(mesh, planes, filt):
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS, None, None), P(SHARD_AXIS, None)),
        out_specs=P(),
    )
    def step(p_blk, f_blk):
        masked = p_blk & f_blk[:, None, :]
        local = jnp.sum(lax.population_count(masked), axis=(0, 2), dtype=jnp.int32)
        return lax.psum(local, SHARD_AXIS)

    return step(planes, filt)


def bsi_sum(mesh: Mesh, planes, filt) -> int:
    """Sum of BSI values across all shards: per-plane popcounts psum'd,
    weighted host-side with exact ints (fragment.sum semantics,
    fragment.go:1111, distributed)."""
    pc = np.asarray(_bsi_plane_counts(mesh, planes, filt))
    # planes layout per shard: [exists, sign-excluded magnitudes...] — the
    # caller passes magnitude planes only, pre-masked by sign.
    return sum(int(c) << i for i, c in enumerate(pc))


@functools.partial(jax.jit, static_argnums=(0,))
def _full_query_step(mesh, row_a, row_b, topn_matrix, planes):
    """The flagship sharded query pipeline as ONE compiled program:
    Count(Intersect) + TopN phase-1 + BSI plane counts, sharing the psum
    tree.  This is what dryrun_multichip compiles and runs."""

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS, None),
            P(SHARD_AXIS, None),
            P(SHARD_AXIS, None, None),
            P(SHARD_AXIS, None, None),
        ),
        out_specs=(P(), P(), P()),
    )
    def step(a_blk, b_blk, mat_blk, p_blk):
        inter = a_blk & b_blk
        count = jnp.sum(lax.population_count(inter), dtype=jnp.int32)
        count = lax.psum(count, SHARD_AXIS)

        masked = mat_blk & inter[:, None, :]
        row_counts = jnp.sum(
            lax.population_count(masked), axis=(0, 2), dtype=jnp.int32
        )
        row_counts = lax.psum(row_counts, SHARD_AXIS)

        plane_counts = jnp.sum(
            lax.population_count(p_blk & a_blk[:, None, :]),
            axis=(0, 2),
            dtype=jnp.int32,
        )
        plane_counts = lax.psum(plane_counts, SHARD_AXIS)
        return count, row_counts, plane_counts

    return step(row_a, row_b, topn_matrix, planes)


def full_query_step(mesh: Mesh, row_a, row_b, topn_matrix, planes):
    return _full_query_step(mesh, row_a, row_b, topn_matrix, planes)
