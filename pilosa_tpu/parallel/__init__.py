"""Parallel execution: per-shard query execution, map-reduce, mesh fan-out.

The TPU-native replacement for the reference's distributed executor
(executor.go): shard-level evaluation runs as fused XLA programs on device
tensors; cross-shard reduce happens host-side single-node and via
shard_map/ICI collectives on a mesh (pilosa_tpu.parallel.mesh).
"""

from pilosa_tpu.parallel.results import (
    ValCount,
    Pair,
    PairField,
    FieldRow,
    GroupCount,
)
from pilosa_tpu.parallel.executor import Executor, ExecOptions

__all__ = [
    "ValCount",
    "Pair",
    "PairField",
    "FieldRow",
    "GroupCount",
    "Executor",
    "ExecOptions",
]
