"""SPMD collective query execution: stacks spanning every process's chips.

The reference's only cross-machine mechanism is HTTP scatter-gather
(`/root/reference/executor.go:2455`): each node computes its shards,
results merge on the coordinator.  That path exists here too (the
control plane's `_map_shards`).  This module is the TPU-native second
gear: ONE global `jax.sharding.Mesh` over every process's devices, query
operands as global arrays whose blocks live where their fragments live,
and XLA collectives (psum over ICI/DCN) doing the reduction — the
scaling-book recipe applied to set algebra.

## The ownership seam, resolved (VERDICT round-2 missing #2)

Control plane and data plane previously disagreed about placement:
fragments live where the jump hash puts them (`cluster.py:69
shard_owners`), while `multihost.local_shard_slice` assumed
block-contiguous ownership.  The resolution: **the control plane's jump
hash is the single source of truth, and the data plane derives its mesh
layout from it.**  A collective plan orders the global shard axis by
(owning process rank, shard id), padding each process's block to a
whole multiple of its device count.  Each process then feeds exactly
its LOCAL fragments into its LOCAL devices' blocks
(`jax.make_array_from_callback` only asks a process for addressable
blocks), so building a global operand moves **zero** bytes between
processes — the only cross-process traffic is the collective reduction
itself.  `local_shard_slice`'s contiguous fiction is gone; plans carry
the real ownership.

Process-rank convention: rank r = position of the node id in
``sorted(node_ids)``, and the launcher must assign
``JAX_PROCESS_ID`` the same way (`verify_rank_convention` asserts it at
startup — a mismatch is a configuration error, caught loudly).

## Execution model

Collectives are SPMD: every process must enter the same program in the
same order.  `collective_query` is therefore called symmetrically — on
a live cluster the coordinator broadcasts the query over the control
plane (`/internal/collective/execute`) and every process joins; tests
drive both processes directly.  Supported calls: bare bitmap trees
(Row/Union/Intersect/Difference/Xor/Not/Shift/Range — the result Row
gathers replicated and the coordinator assembles segments), Count over
those trees (incl. BSI-condition rows, the Range surface), Sum/Min/Max
(optional filter), TopN (optional filter), MinRow/MaxRow (optional
filter), Rows (incl. column/previous/limit and time covers), GroupBy
over N Rows children (incl. column/previous/limit constraints and
time-constrained children via their agreed view cover).  Everything
else stays on the scatter-gather path; key-translated queries
translate before entering (the test covers raw ids)."""

from __future__ import annotations

import functools
import os
import threading
from dataclasses import dataclass

import numpy as np

from pilosa_tpu.models.view import VIEW_STANDARD
from pilosa_tpu.ops import bitmap as bm
from pilosa_tpu.ops import bsi as bsi_ops
from pilosa_tpu.parallel import mesh as pmesh
from pilosa_tpu.shardwidth import SHARD_WIDTH


class CollectiveError(RuntimeError):
    pass


#: Row-cardinality ceilings for the dense collective operands.  The
#: matrix paths build [G, R, words] globals and (GroupBy) an [G, Ra, Rb]
#: gather — fine for the dimensional-field shapes they serve, hostile at
#: high cardinality where the scatter path's pruning level walk already
#: answers well.  The guards raise AFTER agreed_row_ids, which is
#: deterministic and symmetric (same data on every process), so every
#: participant refuses together and the coordinator falls back — nobody
#: is left parked in a half-entered collective.
MAX_COLLECTIVE_ROWS = 4096
MAX_COLLECTIVE_PAIRS = 1 << 22

#: top-level calls whose result is a bitmap (a global Row) — the
#: ordinary read surface (reference executeBitmapCall, executor.go:651)
BITMAP_ROOTS = ("Row", "Range", "Union", "Intersect", "Difference",
                "Xor", "Not", "Shift")

#: per-window byte bound for the replicated bare-bitmap gather.  A
#: [G, words] result wider than this replicates in shard-range
#: windows (each a bounded collective) instead of one all-gather, so
#: ANY index width stays on the collective plane with per-process
#: transient memory capped at one window (round 5; previously a hard
#: ceiling that pushed wide indexes to the scatter plane).  Env knob
#: exists for memory-constrained deployments and for the
#: multi-process test tier to force the windowed path on small data.
MAX_ROW_GATHER_BYTES = int(os.environ.get(
    "PILOSA_TPU_MAX_ROW_GATHER_BYTES", 1 << 28))


@dataclass(frozen=True)
class Plan:
    """One query's agreed global layout — identical on every process."""

    mesh: object                # jax.sharding.Mesh over ALL devices
    order: tuple[int, ...]      # global shard order; -1 = padding block
    local: range                # global indices this process's chips own


def owner_rank_fn(cluster, index_name: str):
    """shard -> process rank under the jump-hash control plane.  Rank =
    position of the owning node id in sorted order (the documented
    launcher convention)."""
    ids = sorted(n.id for n in cluster.sorted_nodes())

    def rank(shard: int) -> int:
        node = cluster.primary_shard_node(index_name, shard)
        return ids.index(node.id)

    return rank


def verify_rank_convention(cluster) -> None:
    """Assert this process's jax process_index matches its node id's
    sorted position — the invariant every plan relies on.  Raises on a
    misconfigured launcher instead of silently mis-placing blocks."""
    import jax

    ids = sorted(n.id for n in cluster.sorted_nodes())
    want = ids.index(cluster.local_id)
    got = jax.process_index()
    if want != got:
        raise CollectiveError(
            f"rank convention violated: node id {cluster.local_id!r} is "
            f"sorted position {want} but jax.process_index() is {got}; "
            f"launch processes with JAX_PROCESS_ID in sorted-node-id "
            f"order")


def make_plan(shards, owner_rank) -> Plan:
    """Owner-grouped global order over every process's devices."""
    import jax

    n_proc = jax.process_count()
    n_local = len(jax.local_devices())
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    if len(devs) != n_proc * n_local:
        raise CollectiveError(
            f"heterogeneous device counts ({len(devs)} global, "
            f"{n_local} local x {n_proc} processes) are unsupported")
    groups: list[list[int]] = [[] for _ in range(n_proc)]
    for s in sorted(shards):
        groups[owner_rank(s)].append(s)
    widest = max((len(g) for g in groups), default=0)
    per = max(n_local, -(-widest // n_local) * n_local)
    order: list[int] = []
    for g in groups:
        order += g + [-1] * (per - len(g))
    from jax.sharding import Mesh

    mesh = Mesh(np.array(devs), (pmesh.SHARD_AXIS,))
    me = jax.process_index()
    return Plan(mesh=mesh, order=tuple(order),
                local=range(me * per, (me + 1) * per))


# ------------------------------------------------------------- operands


def _sharding(plan: Plan, extra_dims: int):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(plan.mesh, P(pmesh.SHARD_AXIS,
                                      *([None] * extra_dims)))


def _fill_blocks(plan: Plan, block_shape, fill_one):
    """A make_array_from_callback callback: zero block, then
    ``fill_one(local_row_buffer, shard_id)`` per non-padding shard."""
    def cb(index):
        sl = index[0]
        block = np.zeros((sl.stop - sl.start,) + block_shape,
                         dtype=np.uint32)
        for i, gi in enumerate(range(sl.start, sl.stop)):
            s = plan.order[gi]
            if s >= 0:
                fill_one(block[i], s)
        return block

    return cb


def global_row_stack(field, row_id: int, plan: Plan):
    """[G, words] global operand for one row; each process fills the
    blocks whose fragments it owns — no cross-process copies."""
    import jax

    view = field.view(VIEW_STANDARD)
    n_words = bm.n_words(SHARD_WIDTH)

    def fill(buf, s):
        frag = view.fragment(s) if view is not None else None
        if frag is not None:
            with frag._lock:
                # EFFECTIVE words (base ⊕ pending ingest delta): the
                # collective path has no dfuse staging, so the overlay
                # applies at fill time
                arr, _ = frag._row_words_effective_locked(row_id)
                if arr is not None:
                    buf[:] = arr

    return jax.make_array_from_callback(
        (len(plan.order), n_words), _sharding(plan, 1),
        _fill_blocks(plan, (n_words,), fill))


def global_time_row_stack(field, row_id: int, view_names, plan: Plan):
    """[G, words] operand for a time-range Row: each block is the OR of
    the covering views' rows from the LOCAL fragments.  The view list
    must be identical on every process — the collective path derives it
    UNCLAMPED from query text + the field's (replicated) quantum, never
    from locally-present views (processes hold different view subsets;
    a local clamp would diverge the programs)."""
    import jax

    views = [field.view(vn) for vn in view_names]
    n_words = bm.n_words(SHARD_WIDTH)

    def fill(buf, s):
        for v in views:
            frag = v.fragment(s) if v is not None else None
            if frag is None:
                continue
            with frag._lock:  # OR under the lock: rows mutate in place
                arr, _ = frag._row_words_effective_locked(row_id)
                if arr is not None:
                    np.bitwise_or(buf, arr, out=buf)

    return jax.make_array_from_callback(
        (len(plan.order), n_words), _sharding(plan, 1),
        _fill_blocks(plan, (n_words,), fill))


def global_plane_stack(field, plan: Plan):
    """[G, planes, words] BSI operand (exists, sign, magnitudes)."""
    import jax

    field._require_int()
    depth = field.options.bit_depth
    n_planes = bsi_ops.OFFSET_PLANE + depth
    view = field.view(field.bsi_view_name)
    n_words = bm.n_words(SHARD_WIDTH)

    def fill(buf, s):
        frag = view.fragment(s) if view is not None else None
        if frag is None:
            return
        with frag._lock:
            for p in range(n_planes):
                arr = frag._rows.get(p)
                if arr is not None:
                    buf[p] = arr

    return jax.make_array_from_callback(
        (len(plan.order), n_planes, n_words), _sharding(plan, 2),
        _fill_blocks(plan, (n_planes, n_words), fill))


def global_matrix_stack(field, row_ids, plan: Plan,
                        view_names=(VIEW_STANDARD,)):
    """[G, R, words] matrix over an AGREED row-id list (TopN/GroupBy
    operand).  The row list must be identical on every process — see
    ``agreed_row_ids``.  With multiple ``view_names`` (time-constrained
    GroupBy children) each block row is the OR of the covering views'
    rows, matching the scatter path's merged-row semantics
    (executor._execute_rows view scan)."""
    import jax

    views = [field.view(vn) for vn in view_names]
    n_words = bm.n_words(SHARD_WIDTH)
    rid_list = list(row_ids)

    def fill(buf, s):
        for v in views:
            frag = v.fragment(s) if v is not None else None
            if frag is None:
                continue
            with frag._lock:  # OR under the lock: rows mutate in place
                for j, rid in enumerate(rid_list):
                    arr, _ = frag._row_words_effective_locked(rid)
                    if arr is not None:
                        np.bitwise_or(buf[j], arr, out=buf[j])

    return jax.make_array_from_callback(
        (len(plan.order), len(rid_list), n_words), _sharding(plan, 2),
        _fill_blocks(plan, (len(rid_list), n_words), fill))


def plan_shards(plan: Plan) -> frozenset:
    """The real (non-padding) shard set a plan covers."""
    return frozenset(s for s in plan.order if s >= 0)


def agreed_row_ids(field, view_names=(VIEW_STANDARD,),
                   shards=None) -> list[int]:
    """The union of row ids across every process, identical everywhere:
    local union (across the agreed view cover, restricted to
    ``shards`` when given — an Options(shards=[...]) plan must not
    list rows living only outside its restriction), then a fixed-size
    allgather (count exchange first, pad to the max).
    Control-plane-free — it rides the same collective runtime as the
    data.  ``view_names`` and ``shards`` must be identical on every
    process (both derive from the agreed query text + plan)."""
    import jax
    from jax.experimental import multihost_utils

    local: set[int] = set()
    for vn in view_names:
        view = field.view(vn)
        if view is not None:
            for shard, frag in list(view.fragments.items()):
                if shards is not None and shard not in shards:
                    continue
                local.update(frag.row_ids())
    if jax.process_count() == 1:
        return sorted(local)
    mine = np.array(sorted(local), dtype=np.int64)
    counts = multihost_utils.process_allgather(
        np.array([len(mine)], dtype=np.int64))
    cap = int(counts.max())
    padded = np.full(cap, -1, dtype=np.int64)
    padded[: len(mine)] = mine
    gathered = multihost_utils.process_allgather(padded)
    ids = np.unique(gathered)
    return [int(r) for r in ids if r >= 0]


# ------------------------------------------------------ collective eval


def _replicated(plan: Plan):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(plan.mesh, P())


@functools.cache
def _jit_count(mesh):
    """Per-shard popcounts [G] int32, gathered replicated: each shard
    holds <= 2^20 bits so int32 never wraps per shard; the cross-shard
    sum runs host-side in int64 (a whole-stack int32 reduce would wrap
    past 2^31 set bits at the 10B scale — same split as the fused
    executor path)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def f(stack):
        return jnp.sum(lax.population_count(stack), axis=1,
                       dtype=jnp.int32)

    return jax.jit(f, out_shardings=NamedSharding(mesh, P()))


@functools.cache
def _jit_sum0(mesh):
    """Sum over the sharded axis, gathered replicated — the reduce for
    tiny indicator stacks (XLA lowers it to one psum over the mesh)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.jit(lambda a: jnp.sum(a, axis=0, dtype=jnp.int32),
                   out_shardings=NamedSharding(mesh, P()))


def global_column_bits(field, row_ids, column: int, plan: Plan,
                       view_names=(VIEW_STANDARD,)) -> np.ndarray:
    """[R] replicated 0/1 per row of ``row_ids``: does the row contain
    ``column``?  The owning shard's block carries the bits read from
    its local fragment; every other block is zero; one mesh sum
    replicates the answer (the collective analog of the executor's
    vectorized column-word read, executor.py map_fn / reference
    rowFilter ColumnFilter fragment.go:2618).  With multiple
    ``view_names`` a row qualifies when the bit is set in ANY covering
    view (merged-row semantics, as the scatter path)."""
    import jax

    shard = column // SHARD_WIDTH
    off = column % SHARD_WIDTH
    views = [field.view(vn) for vn in view_names]

    def fill(buf, s):
        if s != shard:
            return
        for v in views:
            frag = v.fragment(s) if v is not None else None
            if frag is None:
                continue
            with frag._lock:
                for i, r in enumerate(row_ids):
                    # effective bit: honors a pending delta override
                    if frag._bit_off_locked(r, off):
                        buf[i] |= np.uint32(1)

    stack = jax.make_array_from_callback(
        (len(plan.order), len(row_ids)), _sharding(plan, 1),
        _fill_blocks(plan, (len(row_ids),), fill))
    return np.asarray(_jit_sum0(plan.mesh)(stack))


@functools.cache
def _jit_gather(mesh):
    """Replicate a sharded [G, words] result stack to every process —
    one all-gather over the mesh.  The bare-bitmap result path: the
    coordinator assembles the global Row host-side from the replicated
    copy (every process runs the identical program; peers discard)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.jit(lambda stack: stack,
                   out_shardings=NamedSharding(mesh, P()))


@functools.cache
def _jit_exists(mesh):
    """planes[:, EXISTS] as a sharded [G, words] stack — eager slicing
    of a multi-process global array is illegal outside jit."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.jit(lambda planes: planes[:, bsi_ops.EXISTS_PLANE],
                   out_shardings=NamedSharding(
                       mesh, P(pmesh.SHARD_AXIS, None)))


@functools.cache
def _jit_row_counts(mesh, masked: bool):
    """Per-(shard, row) popcounts [G, R] int32, gathered replicated —
    the cross-shard sum runs host-side in int64, same wrap discipline
    as _jit_count (an on-device axis-0 int32 reduce would wrap past
    2^31 set bits per row at the 10B scale).  The [G, R] gather is
    never the bottleneck: the matrix operand itself is W/R times
    larger."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    if masked:
        def f(mat, filt):
            return jnp.sum(lax.population_count(mat & filt[:, None, :]),
                           axis=2, dtype=jnp.int32)
    else:
        def f(mat):
            return jnp.sum(lax.population_count(mat), axis=2,
                           dtype=jnp.int32)
    return jax.jit(f, out_shardings=NamedSharding(mesh, P()))


@functools.cache
def _jit_plane_counts(mesh):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def f(planes, consider):
        sign = planes[:, bsi_ops.SIGN_PLANE]
        prow = consider & ~sign
        nrow = consider & sign
        mags = planes[:, bsi_ops.OFFSET_PLANE:]
        # per-plane counts summed over shards AND words; per-shard
        # magnitudes fit int32 (<= 2^20 columns/shard), and the shard
        # reduction is per-plane int32 counts -> at most G * 2^20 which
        # can exceed int32 at extreme G, so split: per-shard int32,
        # host sums in int64.  Shape [G, depth] stays sharded until the
        # out_sharding gathers it.
        pos = jnp.sum(lax.population_count(mags & prow[:, None, :]),
                      axis=2, dtype=jnp.int32)
        neg = jnp.sum(lax.population_count(mags & nrow[:, None, :]),
                      axis=2, dtype=jnp.int32)
        cnt = jnp.sum(lax.population_count(consider), axis=1,
                      dtype=jnp.int32)
        return pos, neg, cnt

    return jax.jit(f, out_shardings=NamedSharding(mesh, P()))


@functools.cache
def _jit_pair_counts(mesh, filtered: bool):
    """GroupBy(2 children) pair counts: [G, Ra, Rb] per-shard int32,
    gathered replicated (host sums shards in int64).  The cartesian
    broadcast fuses into the popcount reduction — nothing materializes
    at [G, Ra, Rb, W].  Collective v1 serves the common 1-2 child
    shapes; deeper nests use the scatter path's padded level walk."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    if filtered:
        def f(mat_a, mat_b, filt):
            inter = (mat_a[:, :, None, :] & mat_b[:, None, :, :]
                     & filt[:, None, None, :])
            return jnp.sum(lax.population_count(inter), axis=3,
                           dtype=jnp.int32)
    else:
        def f(mat_a, mat_b):
            inter = mat_a[:, :, None, :] & mat_b[:, None, :, :]
            return jnp.sum(lax.population_count(inter), axis=3,
                           dtype=jnp.int32)
    return jax.jit(f, out_shardings=NamedSharding(mesh, P()))


@functools.cache
def _jit_extremes(mesh, want: str):
    """Batched Min/Max scan over the global plane stack, all six
    per-shard outputs gathered replicated — the host applies the same
    sign branching as the fused executor path (fragment.min/max
    semantics, fragment.go:1147/1191)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def f(planes, consider):
        return bsi_ops.extremes_stacked(planes, consider, want)

    return jax.jit(f, out_shardings=NamedSharding(mesh, P()))


@functools.cache
def _jit_range_stack(mesh, op: str, p1: int, p2: int):
    """BSI compare -> [G, words] sharded row stack (stays sharded; the
    caller counts or combines it).  Static predicates: query text
    compiles per distinct (op, value) like the fused path."""
    import jax

    def f(planes):
        if op == "between":
            return jax.vmap(
                lambda Ps: bsi_ops.between_words(Ps, p1, p2))(planes)
        return jax.vmap(
            lambda Ps: bsi_ops.range_words(Ps, op, p1))(planes)

    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.jit(f, out_shardings=NamedSharding(
        mesh, P(pmesh.SHARD_AXIS, None)))


# --------------------------------------------------- server integration

#: One collective at a time per process.  Initiation is further
#: restricted to the coordinator, so cluster-wide ordering is the
#: coordinator's initiation order — peers can never observe two
#: collectives interleaved.
_collective_lock = threading.Lock()

_counters_lock = threading.Lock()
_counters = {
    "collective_initiated": 0,  # coordinator ran a query collectively
    "collective_joined": 0,     # this process joined a peer's collective
    "collective_fallbacks": 0,  # collective failed; scatter path answered
}


def _bump(name: str) -> None:
    with _counters_lock:
        _counters[name] += 1


def counters() -> dict:
    with _counters_lock:
        return dict(_counters)


def prometheus_lines() -> str:
    out = []
    for name, v in sorted(counters().items()):
        m = f"pilosa_spmd_{name}_total"
        out.append(f"# TYPE {m} counter")
        out.append(f"{m} {v}")
    return "\n".join(out) + "\n"


def collective_available() -> bool:
    """True only in a jax.distributed multi-process runtime.  Checked
    via multihost's explicit flag first so single-host servers never
    force a backend init from the query path."""
    from pilosa_tpu.parallel import multihost

    if not multihost._initialized_distributed:
        return False
    import jax

    return jax.process_count() > 1


def _call_time_field(idx, c):
    """The time-quantum field a call's from/to args refer to, or None
    (no field arg, unknown field, not a time field)."""
    if c.name == "Rows":
        fname = c.args.get("_field") or c.args.get("field")
    else:
        try:
            fname = c.field_arg()
        except Exception:  # noqa: BLE001 — malformed: supported() refuses
            fname = None
    if not fname:
        return None
    f = idx.field(fname)
    return f if (f is not None and f.time_quantum) else None


def _needs_time_bounds(c, f, top: bool = False) -> bool:
    """Does this call carry an under-specified time range the
    coordinator must resolve to concrete global values?  Row/Range:
    exactly one of from=/to=.  Rows: a STANDALONE (top-level) call
    engages the time-view scan for from/to or a no-standard-view
    field and needs both bounds concrete; a GroupBy CHILD only needs
    bounds when constrained (the pre-selection is the only place time
    bites there — reference executeGroupBy pre-executes solely for
    limit/column, executor.go:1104-1117, and newGroupByIterator
    always scans viewStandard, executor.go:3102; a no-standard-view
    child is constant-empty before any bound is consulted, so
    resolving would only add a pointless peer round)."""
    has_from, has_to = "from" in c.args, "to" in c.args
    if c.name in ("Row", "Range"):
        return has_from != has_to
    if c.name == "Rows":
        if not top:
            if f.options.no_standard_view:
                return False  # constant-empty GroupBy child
            if not any(k in c.args
                       for k in ("limit", "column", "previous")):
                return False  # unconstrained child: from/to ignored
        if f.options.no_standard_view:
            return not (has_from and has_to)
        if not (has_from or has_to):
            return False
        return has_from != has_to
    return False


def _open_time_fields(idx, call) -> set:
    """Field names of time-range calls in the tree carrying an
    under-specified bound (see _needs_time_bounds).  Only fields that
    exist with a time quantum count — anything else is the supported()
    check's problem."""
    from pilosa_tpu.pql import Call as _Call

    out = set()

    def walk(c, top: bool) -> None:
        if not isinstance(c, _Call):
            return
        f = _call_time_field(idx, c)
        if f is not None and _needs_time_bounds(c, f, top=top):
            out.add(f.name)
        filt = c.args.get("filter")
        if isinstance(filt, _Call):
            walk(filt, False)
        for ch in c.children:
            # Options is transparent: Options(Rows(...)) is still a
            # STANDALONE Rows for the bounds rules
            walk(ch, top and c.name == "Options")

    walk(call, True)
    return out


#: rewrite target when NO process holds any time view: a concrete
#: empty range (start == end), so every program agrees on "no cover"
_EMPTY_RANGE_TS = "1970-01-01T00:00"


def _resolve_open_time_ranges(node, idx, index_name: str, call):
    """Rewrite open-ended time-range bounds to concrete global values
    IN THE QUERY TEXT, so the SPMD programs stay identical everywhere.

    The scatter path clamps open-ended ranges per node against locally
    present views (executor._clamp_to_views, mirroring the reference's
    minMaxViews in executeRowsShard) — but processes hold different
    view subsets, so a local clamp would diverge the collective
    programs.  Instead the coordinator gathers every process's view
    time bounds over the control plane (one `collective-time-bounds`
    round) and writes the GLOBAL clamp into the call args; the
    rewritten text ships to peers, and clamping to the global view
    span is result-identical to the per-node clamp (views outside a
    node's span contribute nothing anywhere).

    Mutates and returns `call` (origin-private: parsed from text by
    the caller).  Raises CollectiveError when a peer cannot answer —
    the caller falls back to the scatter path."""
    import datetime as _dt

    from pilosa_tpu.models.timequantum import TIME_FORMAT

    fields = _open_time_fields(idx, call)
    if not fields:
        return call

    bounds: dict = {}

    def merge(fname, lo, hi):
        cur = bounds.get(fname)
        bounds[fname] = ((lo, hi) if cur is None
                         else (min(cur[0], lo), max(cur[1], hi)))

    for fname in fields:
        f = idx.field(fname)
        times = f.time_view_times()
        bounds[fname] = None
        if times:
            merge(fname, min(times), max(times))
    peers = [n for n in node.cluster.sorted_nodes()
             if n.id != node.cluster.local_id]
    for n in peers:
        r = node.cluster.transport.send_message(
            n, {"type": "collective-time-bounds", "index": index_name,
                "fields": sorted(fields)})
        if not r.get("ok"):
            raise CollectiveError(
                f"peer {n.id} time bounds: {r.get('error')}")
        for fname, pair in (r.get("bounds") or {}).items():
            if pair is not None:
                merge(fname,
                      _dt.datetime.strptime(pair[0], TIME_FORMAT),
                      _dt.datetime.strptime(pair[1], TIME_FORMAT))

    from pilosa_tpu.pql import Call as _Call

    def rewrite(c, top: bool = False):
        if not isinstance(c, _Call):
            return
        f = _call_time_field(idx, c)
        if (f is not None and f.name in bounds
                and _needs_time_bounds(c, f, top=top)):
            span = bounds[f.name]
            if span is None:
                # no time views anywhere: concrete empty range
                c.args["from"] = _EMPTY_RANGE_TS
                c.args["to"] = _EMPTY_RANGE_TS
            else:
                lo, hi = span
                # same widening as executor._clamp_to_views: the
                # max view START plus the widest view unit (a year
                # view covers 366 days of data)
                if "from" not in c.args:
                    c.args["from"] = lo.strftime(TIME_FORMAT)
                if "to" not in c.args:
                    c.args["to"] = (hi + _dt.timedelta(days=366)
                                    ).strftime(TIME_FORMAT)
        filt = c.args.get("filter")
        if isinstance(filt, _Call):
            rewrite(filt)
        for ch in c.children:
            rewrite(ch, top=top and c.name == "Options")

    rewrite(call, top=True)
    return call


def _has_sentinel(call) -> bool:
    """True when translation produced an internal sentinel call
    (_Empty/_EmptyRows/_Noop) anywhere in the tree.  (Since round 5
    the sentinels DO re-parse as text — the scatter path ships them to
    peers directly — but the COLLECTIVE evaluator has no sentinel
    stacks, so this plane still folds them out algebraically or
    declines in favor of scatter.)"""
    if call.name.startswith("_"):
        return True
    filt = call.args.get("filter")
    from pilosa_tpu.pql import Call as _Call

    if isinstance(filt, _Call) and _has_sentinel(filt):
        return True
    return any(_has_sentinel(c) for c in call.children)


#: marker: a bitmap subtree provably folded to the empty bitmap
_EMPTY_TREE = object()


def _fold_bitmap_tree(call):
    """Fold ``_Empty`` sentinels out of a translated BITMAP tree by
    set algebra, so a missing read key no longer forces the whole
    query onto the scatter path (reference semantics: a missing key is
    an empty row, executor.go:2610 translateCalls).

    Returns the folded tree (a Call with no sentinels), ``_EMPTY_TREE``
    when the subtree is provably the empty bitmap, or ``None`` when a
    sentinel sits where algebra cannot remove it — ``Not(empty)`` is
    the full existence set, which has no PQL spelling to ship to
    peers, and ``_EmptyRows``/``_Noop`` never fold."""
    from pilosa_tpu.pql import Call as _Call

    name = call.name
    if name == "_Empty":
        return _EMPTY_TREE
    if name.startswith("_"):
        return None  # _EmptyRows/_Noop: not a bitmap-algebra sentinel
    if not any(_has_sentinel(c) for c in call.children):
        return call  # untouched subtree ships verbatim
    kids = []
    for c in call.children:
        k = _fold_bitmap_tree(c)
        if k is None:
            return None
        kids.append(k)
    if name == "Union":
        real = [k for k in kids if k is not _EMPTY_TREE]
        if not real:
            return _EMPTY_TREE
        return real[0] if len(real) == 1 else _Call(name, dict(call.args), real)
    if name == "Intersect":
        if any(k is _EMPTY_TREE for k in kids):
            return _EMPTY_TREE
        return _Call(name, dict(call.args), kids)
    if name == "Difference":
        # Difference(a, b, c, ...) = a \ (b | c | ...)
        if kids and kids[0] is _EMPTY_TREE:
            return _EMPTY_TREE
        real = kids[:1] + [k for k in kids[1:] if k is not _EMPTY_TREE]
        if len(real) == 1:
            return real[0]
        return _Call(name, dict(call.args), real)
    if name == "Xor":
        # empty is the identity of symmetric difference
        real = [k for k in kids if k is not _EMPTY_TREE]
        if not real:
            return _EMPTY_TREE
        return real[0] if len(real) == 1 else _Call(name, dict(call.args), real)
    if name == "Shift":
        if kids[0] is _EMPTY_TREE:
            return _EMPTY_TREE
        return _Call(name, dict(call.args), kids)
    if name == "Not":
        # Not(empty) = the existence set: correct, but unshippable as
        # text — decline and let the scatter path answer it
        if kids[0] is _EMPTY_TREE:
            return None
        return _Call(name, dict(call.args), kids)
    return None


def _fold_query(call):
    """Coordinator-side sentinel fold of one top-level read call.
    Returns a sentinel-free Call ready to ship, or ``None`` when the
    query (or its whole operand tree) cannot be folded to shippable
    text — including the whole-tree-empty case, which the scatter
    path's native sentinel handling answers with exactly the
    reference's empty-row semantics."""
    from pilosa_tpu.pql import Call as _Call

    if call.name.startswith("_"):
        return None
    args = call.args
    filt = args.get("filter")
    if isinstance(filt, _Call) and _has_sentinel(filt):
        folded = _fold_bitmap_tree(filt)
        if folded is None or folded is _EMPTY_TREE:
            return None
        args = dict(args)
        args["filter"] = folded
        call = _Call(call.name, args, list(call.children))
    if not any(_has_sentinel(c) for c in call.children):
        return call if not _has_sentinel(call) else None
    if call.name in ("Count", "Sum", "Min", "Max", "TopN",
                     "MinRow", "MaxRow"):
        # the single child is a bitmap filter tree
        kids = [_fold_bitmap_tree(c) for c in call.children]
        if any(k is None or k is _EMPTY_TREE for k in kids):
            return None
        return _Call(call.name, dict(call.args), kids)
    if call.name in BITMAP_ROOTS:
        folded = _fold_bitmap_tree(call)
        if folded is None or folded is _EMPTY_TREE:
            # whole-tree-empty: the scatter path's native sentinel
            # handling answers with the reference's empty-row semantics
            return None
        return folded
    if call.name == "Options" and len(call.children) == 1:
        inner = _fold_query(call.children[0])
        if inner is None:
            return None
        return _Call(call.name, dict(call.args), [inner])
    return None  # GroupBy children are Rows calls, not bitmap algebra


def _check_collective(node, index_name: str, pql: str,
                      translate: bool = False):
    """Shared pre-flight validation (no locks, no device work).
    Returns ``(reason, translated_pql, translated_call)``: reason is
    the string explaining why this process can NOT run the query
    collectively (None = it can).  With ``translate=True`` (the
    coordinator) string keys rewrite to ids ONCE at the origin —
    exactly the reference's origin-only translation (executor.go:146)
    — and the translated text is what ships to peers, so the prepare
    round and every participant evaluate an id-only program."""
    if not collective_available():
        return "not a multi-process runtime", None, None
    idx = node.holder.index(index_name)
    if idx is None:
        return f"unknown index {index_name!r}", None, None
    from pilosa_tpu.pql import parse

    try:
        calls = parse(pql).calls
    except Exception as e:  # noqa: BLE001
        return f"parse error: {e!r}", None, None
    if len(calls) != 1:
        return "multi-call query", None, None
    call = calls[0]
    gate = call
    while gate.name == "Options" and gate.children:
        gate = gate.children[0]  # the gate must see THROUGH Options:
        # Options(Set(...)) is still a write
    if (gate.name not in ("Count", "Sum", "Min", "Max", "TopN", "GroupBy",
                          "Rows", "MinRow", "MaxRow")
            and gate.name not in BITMAP_ROOTS):
        # cheap refusal BEFORE any translation: writes and other
        # non-collective calls must not pay a cloned translate (with
        # create=True key allocation for Set) that the scatter path
        # immediately repeats
        return f"unsupported call {gate.name}", None, None
    if translate:
        try:
            call = node.executor._translate_call(idx, call)
        except Exception as e:  # noqa: BLE001 — scatter path owns the error
            return f"translation failed: {e!r}", None, None
        if _has_sentinel(call):
            # a missing key translated to an _Empty/_Noop sentinel.
            # The collective evaluator has no sentinel stacks (the
            # scatter path evaluates them natively, and since round 5
            # their text form even ships to peers), so fold them out
            # by set algebra where possible (Union drops empty
            # children, Intersect collapses, ...); only unfoldable
            # shapes — whole-tree-empty, Not(empty), _EmptyRows — fall
            # back to the scatter path's native sentinel handling
            folded = _fold_query(call)
            if folded is None:
                return ("missing-key sentinel in translated query",
                        None, None)
            call = folded
        try:
            call = _resolve_open_time_ranges(node, idx, index_name, call)
        except Exception as e:  # noqa: BLE001 — scatter path owns it
            return f"open time-range resolution failed: {e!r}", None, None
        pql = str(call)
    ce = CollectiveExecutor(node.holder, node.cluster, index_name)
    if not ce.supported(call):
        return f"unsupported call {call.name}", None, None
    try:
        verify_rank_convention(node.cluster)
    except CollectiveError as e:
        return str(e), None, None
    return None, pql, call


def try_collective(node, index_name: str, pql: str,
                   exclude_row_attrs: bool = False):
    """Coordinator-side upgrade of one user query to collective SPMD
    execution.  Returns a result list, or None to fall back to the
    scatter-gather plane (not applicable, a peer refused during the
    prepare round, or a collective-runtime failure — logged, never
    raised: the scatter path answers every query the collective one
    can).

    Two-phase entry, because JAX collectives are all-or-hang: a
    synchronous PREPARE round first (each peer validates the query and
    promises to enter — pure control-plane, no device work, no lock),
    then the EXECUTE broadcast fires asynchronously and this process
    enters the collective only after every peer has promised.  A peer
    that DIES between promise and entry is a fail-stop event, not a
    raised error: the jax.distributed coordination service declares
    the world unhealthy after heartbeat_timeout_seconds (measured:
    the survivor is TERMINATED by the runtime, client.h:80 — an
    exception is never delivered to parked participants).  Bounded,
    never a deadlock — but it takes every participating server process
    down; durability is WAL-carried and restart heals (the fate
    coupling is inherent to an SPMD world: survivors could not answer
    collectively without the dead peer's shards anyway).  Operators
    size the detection latency via PILOSA_TPU_DIST_HEARTBEAT_S
    (multihost.initialize).  The HTTP scatter plane keeps replica
    failover for node death on non-collective queries.

    Deadlock discipline (learned against real processes): the join
    broadcast must be in flight BEFORE this process enters the
    collective, and nothing inside the lock may wait on a peer's HTTP
    response except the collective itself — a peer parked inside the
    collective cannot serve anything the collective's completion
    depends on."""
    from pilosa_tpu.parallel.cluster import STATE_NORMAL

    cluster = node.cluster
    if not collective_available():
        return None
    if not cluster.is_coordinator or cluster.state != STATE_NORMAL:
        return None
    user_pql = pql
    reason, pql, tcall = _check_collective(node, index_name, pql,
                                           translate=True)
    if reason is not None:
        return None
    with _collective_lock:
        peers = [n for n in cluster.sorted_nodes()
                 if n.id != cluster.local_id]

        # phase 1: every peer validates and promises (synchronous).
        # The coordinator's MAX_ROW_GATHER_BYTES rides along: the value
        # shapes the windowed-gather program, so env drift between SPMD
        # processes would mean different programs — a silent hang.  A
        # mismatching peer REFUSES here and the query falls back to the
        # scatter plane instead.
        def prepare(n):
            r = node.cluster.transport.send_message(
                n, {"type": "collective-prepare",
                    "index": index_name, "query": pql,
                    "rowGatherBytes": MAX_ROW_GATHER_BYTES})
            if not r.get("ok"):
                raise CollectiveError(
                    f"peer {n.id} refused: {r.get('error')}")

        try:
            for n in peers:
                prepare(n)
        except Exception as e:  # noqa: BLE001 — any refusal: scatter path
            _bump("collective_fallbacks")
            node.executor.logger.printf(
                "collective prepare failed (%r); falling back to "
                "scatter-gather", e)
            return None

        # phase 2: fire the joins and enter
        def ask(n):
            try:
                node.cluster.transport.send_message(
                    n, {"type": "collective-execute",
                        "index": index_name, "query": pql,
                        "rowGatherBytes": MAX_ROW_GATHER_BYTES})
            except Exception:  # noqa: BLE001 — bounded by the runtime timeout
                pass

        threads = [threading.Thread(target=ask, args=(n,), daemon=True)
                   for n in peers]
        for t in threads:
            t.start()
        ce = CollectiveExecutor(node.holder, cluster, index_name)
        try:
            result = ce.execute(pql)
        except Exception as e:  # noqa: BLE001 — fall back, never 500
            _bump("collective_fallbacks")
            node.executor.logger.printf(
                "collective execution failed (%r); falling back to "
                "scatter-gather (peers unpark via the collective "
                "runtime's own timeout)", e)
            for t in threads:
                # pilosa-lint: allow(blocking-under-lock) -- the collective plane is single-flight process-wide BY DESIGN: _collective_lock serializes entire executions including peer fan-out, and no other path takes it
                t.join(timeout=60)
            return None
        for t in threads:
            # pilosa-lint: allow(blocking-under-lock) -- same single-flight collective-plane design as the fallback join above
            t.join(timeout=60)
        # ids -> keys in the result, at the origin only (the reference's
        # translateResults, executor.go:2781), plus row-attr attachment
        # for plain Row results (executor.go:206 — coordinator-side
        # only; attr stores are AE-synced and peers discard).  Guarded:
        # a concurrent index delete or a transient read-through
        # translate failure must fall back, never 500 an answerable
        # query.
        try:
            idx = node.holder.index(index_name)
            from pilosa_tpu.models.row import Row as _Row

            # Options(...) wraps: unwrap for the attr decision, and
            # ASSIGN its excludeRowAttrs like the scatter executor
            # (bool(value) — an explicit false overrides the URL-level
            # flag there too; inner nesting levels override outer)
            acall = tcall
            while acall.name == "Options" and acall.children:
                if "excludeRowAttrs" in acall.args:
                    exclude_row_attrs = bool(
                        acall.args["excludeRowAttrs"])
                acall = acall.children[0]
            if (isinstance(result, _Row) and not exclude_row_attrs
                    and acall.name == "Row"
                    and not acall.has_condition_arg()):
                # attach only when the USER wrote a literal Row():
                # sentinel folding can collapse Union(Row, ghost) to a
                # Row, but the scatter plane (and the reference,
                # executor.go:206) key off the original call name —
                # the planes must serialize identically
                from pilosa_tpu.pql import parse as _parse

                ocall = _parse(user_pql).calls[0]
                if ocall.name == "Options" and ocall.children:
                    ocall = ocall.children[0]
                if ocall.name == "Row":
                    fname = acall.field_arg()
                    rowid = acall.args.get(fname)
                    f = idx.field(fname)
                    if f is not None and isinstance(rowid, int):
                        result.attrs = f.row_attrs.attrs(rowid)
            result = node.executor._translate_result(idx, tcall, result)
        except Exception as e:  # noqa: BLE001
            _bump("collective_fallbacks")
            node.executor.logger.printf(
                "collective result translation failed (%r); falling "
                "back to scatter-gather", e)
            return None
        _bump("collective_initiated")
        return [result]


def _gather_bytes_mismatch(row_gather_bytes) -> str | None:
    """Cross-process agreement check for the env-derived window bound.
    MAX_ROW_GATHER_BYTES is read from the environment at import time
    and drives collective program shape — if the coordinator's value
    differs from ours, entering the collective would hang every
    participant (different windowed-gather programs), so the mismatch
    must surface as a loud refusal instead."""
    if row_gather_bytes is None:  # pre-upgrade coordinator: no claim
        return None
    if int(row_gather_bytes) == MAX_ROW_GATHER_BYTES:
        return None
    return (f"row-gather-bytes mismatch: coordinator has "
            f"{int(row_gather_bytes)}, this process has "
            f"{MAX_ROW_GATHER_BYTES}; set "
            f"PILOSA_TPU_MAX_ROW_GATHER_BYTES identically on every "
            f"process")


def prepare_collective(node, index_name: str, pql: str,
                       row_gather_bytes=None) -> dict:
    """Peer-side prepare: validate without entering (no lock, no device
    work) and promise to join.  The query text arrives PRE-TRANSLATED
    by the coordinator (origin-only translation)."""
    reason = _gather_bytes_mismatch(row_gather_bytes)
    if reason is None:
        reason, _, _ = _check_collective(node, index_name, pql)
    if reason is not None:
        return {"ok": False, "error": reason}
    return {"ok": True}


def join_collective(node, index_name: str, pql: str,
                    row_gather_bytes=None) -> None:
    """Peer-side entry: re-validate (state may have moved since the
    promise), then run the same collective program; the replicated
    result is discarded (the coordinator answers the client)."""
    reason = (_gather_bytes_mismatch(row_gather_bytes)
              or _check_collective(node, index_name, pql)[0])
    if reason is not None:
        raise CollectiveError(reason)
    with _collective_lock:
        CollectiveExecutor(node.holder, node.cluster,
                           index_name).execute(pql)
    _bump("collective_joined")


class CollectiveExecutor:
    """Evaluates one PQL read collectively across every process.

    Construct per (holder, cluster, index); every process must call
    ``execute`` with the same query string in the same order (the
    server's broadcast hook guarantees this on a live cluster)."""

    def __init__(self, holder, cluster, index_name: str):
        self.holder = holder
        self.cluster = cluster
        self.index_name = index_name
        self.idx = holder.index(index_name)
        if self.idx is None:
            raise CollectiveError(f"unknown index {index_name!r}")

    # -- plan

    def _plan(self, shard_filter=None) -> Plan:
        """Global plan over the index's shards — or the
        Options(shards=[...]) list intersected with
        available_shards().  Absent shards contribute zero blocks on
        both planes, so the intersection is semantics-preserving; it
        also bounds the dense operand stacks by what actually exists
        (an hostile shards=[0..10^6] list must not size gigabytes of
        device buffers)."""
        avail = set(self.idx.available_shards())
        if shard_filter is not None:
            shards = sorted({int(s) for s in shard_filter} & avail)
        else:
            shards = sorted(avail)
        return make_plan(shards, owner_rank_fn(self.cluster,
                                               self.index_name))

    # -- eval

    def supported(self, call) -> bool:
        try:
            return self._supported(call)
        except Exception:  # noqa: BLE001 — malformed args are simply
            # not collectively supported; the scatter path owns the
            # user-facing error (try_collective must never raise)
            return False

    #: Options() argument surface (reference executeOptionsCall,
    #: executor.go:3180): serialization flags + a shard restriction
    _OPTIONS_ARGS = frozenset(
        {"columnAttrs", "excludeRowAttrs", "excludeColumns", "shards"})

    def _supported(self, call) -> bool:
        if call.name == "Options":
            if len(call.children) != 1:
                return False
            if not set(call.args) <= self._OPTIONS_ARGS:
                return False  # unknown option: scatter owns the error
            shards = call.args.get("shards")
            if shards is not None and not (
                    isinstance(shards, list)
                    and all(isinstance(s, int) for s in shards)):
                return False
            return self._supported(call.children[0])
        if call.name in BITMAP_ROOTS:
            # bare bitmap result: the whole tree evaluates as one
            # collective program and the global Row replicates — in
            # one all-gather, or in MAX_ROW_GATHER_BYTES shard-range
            # windows on indexes too wide for a single replicated
            # stack (no width limit on collective support).
            return self._tree_ok(call)
        if call.name == "Count":
            return (len(call.children) == 1
                    and self._tree_ok(call.children[0]))
        if call.name in ("Sum", "Min", "Max"):
            fname = call.string_arg("field") or call.string_arg("_field")
            if not fname or not self._plain_field(fname):
                return False
            return not call.children or self._tree_ok(call.children[0])
        if call.name in ("MinRow", "MaxRow"):
            fname = call.string_arg("field") or call.args.get("field")
            if not fname or not self._plain_field(fname):
                return False
            return not call.children or self._tree_ok(call.children[0])
        if call.name == "Rows":
            fname = call.args.get("_field") or call.args.get("field")
            if not fname or not self._plain_field(fname):
                return False
            # standalone Rows honors from/to (unlike GroupBy children):
            # the cover must be collectively derivable
            return self._rows_views(self.idx.field(fname), call) \
                is not None
        if call.name == "TopN":
            fname = call.string_arg("_field") or call.args.get("_field")
            if not fname or not self._plain_field(fname):
                return False
            # attrName without a list attrValues is a user error the
            # scatter path owns; the filter itself runs host-side
            # post-count (AE-synced attr stores, coordinator's answer)
            if ("attrName" in call.args
                    and not isinstance(call.args.get("attrValues"), list)):
                return False
            # malformed args: let the scatter path raise the user error
            if (call.uint_arg("tanimotoThreshold") or 0) > 100:
                return False
            return not call.children or self._tree_ok(call.children[0])
        if call.name == "GroupBy":
            if not call.children:
                return False
            if any(a in call.args for a in ("previous", "aggregate",
                                            "having")):
                return False
            for child in call.children:
                if child.name != "Rows":
                    return False
                fname = (child.args.get("_field")
                         or child.args.get("field"))
                if not fname or not self._plain_field(fname):
                    return False
                if self.idx.field(fname).options.no_standard_view:
                    continue  # constant-empty child (see _group_by)
                if (self._child_constrained(child)
                        and self._child_selection_views(child) is None):
                    return False  # unresolved/oversized time cover
            filt = call.call_arg("filter")
            return filt is None or self._tree_ok(filt)
        return False

    def _plain_field(self, name: str) -> bool:
        # keyed fields are fine HERE: the coordinator translates keys
        # to ids before any collective text ships (try_collective), so
        # every arg this evaluator sees is id-space; _translate_result
        # re-keys the answer at the origin
        return self.idx.field(name) is not None

    def _tree_ok(self, call) -> bool:
        if call.name in ("Row", "Range"):
            if "from" in call.args or "to" in call.args:
                fname = call.field_arg()
                if not fname or not self._plain_field(fname):
                    return False
                if type(call.args.get(fname)) is not int:
                    return False
                return self._time_views(call) is not None
            cond = call.condition_arg()
            if cond is not None:
                return self._plain_field(cond[0])
            fname = call.field_arg()
            if not fname or not self._plain_field(fname):
                return False
            # keyed/boolean row args need the translation layer — only
            # plain integer row ids run collectively (bool is an int
            # subclass, hence the exact type check)
            return type(call.args.get(fname)) is int
        if call.name == "Not":
            return (len(call.children) == 1
                    and self.idx.existence_field() is not None
                    and self._tree_ok(call.children[0]))
        if call.name == "Shift":
            n = call.int_arg("n")
            return (len(call.children) == 1 and (n is None or n >= 0)
                    and self._tree_ok(call.children[0]))
        if call.name in ("Union", "Intersect", "Difference", "Xor"):
            return all(self._tree_ok(c) for c in call.children)
        return False

    #: time-range covers beyond this are declined to the scatter path
    #: (an unclamped multi-century cover would compile huge programs)
    MAX_TIME_VIEWS = 256

    def _views_for_range(self, f, from_arg, to_arg) -> list[str] | None:
        """Covering view names for a concrete [from, to) on a time
        field, derived ONLY from query text + the field's replicated
        quantum — every process computes the identical list (a clamp
        against locally present views, as the per-node fused path does,
        would diverge the SPMD programs).  None = not collectively
        evaluable (bad range, open-ended, or cover too wide)."""
        from pilosa_tpu.models.timequantum import (parse_time,
                                                   views_by_time_range)

        if not f.time_quantum:
            return None
        if from_arg is None or to_arg is None:
            return None  # open-ended: needs the local clamp, scatter path
        try:
            start = parse_time(from_arg)
            end = parse_time(to_arg)
        except (ValueError, TypeError, OverflowError, OSError):
            # int timestamps can overflow fromtimestamp (platform time_t)
            return None
        if start >= end:
            return []
        views = list(views_by_time_range(VIEW_STANDARD, start, end,
                                         f.time_quantum))
        return views if len(views) <= self.MAX_TIME_VIEWS else None

    def _time_views(self, call) -> list[str] | None:
        """Covering views for a Row(from=, to=)/Range call."""
        f = self._field(call.field_arg())
        return self._views_for_range(f, call.args.get("from"),
                                     call.args.get("to"))

    @staticmethod
    def _child_constrained(child) -> bool:
        """Does this GroupBy Rows child trigger the cluster-wide row
        pre-selection (scatter: _execute_group_by pre-executes
        _execute_rows for limit/column/previous)?"""
        return any(child.uint_arg(k) is not None
                   for k in ("limit", "column", "previous"))

    def _rows_views(self, f, call) -> list[str] | None:
        """View cover for a STANDALONE Rows call, mirroring the scatter
        path (_execute_rows view selection): a time field scans the
        covering time views when from=/to= is present or the field has
        no standard view (bounds must arrive concrete — the
        coordinator's resolution rewrote open ends); everything else
        scans standard and IGNORES from/to like the reference."""
        if f.time_quantum and ("from" in call.args or "to" in call.args
                               or f.options.no_standard_view):
            return self._views_for_range(f, call.args.get("from"),
                                         call.args.get("to"))
        return [VIEW_STANDARD]

    def _child_selection_views(self, child) -> list[str] | None:
        """View cover for a CONSTRAINED GroupBy Rows child's row
        pre-selection, mirroring the scatter path (_execute_rows view
        selection): a non-time field ignores from=/to= and selects
        from standard; a time field selects from the covering time
        views when from=/to= is present.  Counts always come from
        viewStandard regardless (reference newGroupByIterator,
        executor.go:3102); no-standard-view children never reach here
        — both callers short-circuit them to the constant-empty
        result first.  Returns view names, [] for a provably empty
        range, or None when not collectively evaluable (open-ended
        bounds must already be resolved by the coordinator's
        _resolve_open_time_ranges rewrite)."""
        fname = child.args.get("_field") or child.args.get("field")
        f = self._field(fname)
        if f.time_quantum and ("from" in child.args
                               or "to" in child.args):
            return self._views_for_range(f, child.args.get("from"),
                                         child.args.get("to"))
        return [VIEW_STANDARD]

    def execute(self, pql: str):
        from pilosa_tpu.pql import parse

        calls = parse(pql).calls
        if len(calls) != 1:
            raise CollectiveError("collective execution is per-call")
        call = calls[0]
        if not self.supported(call):
            raise CollectiveError(f"unsupported collective call: "
                                  f"{call.name}")
        opt_args: dict = {}
        while call.name == "Options":
            # unwrap (reference executeOptionsCall, which recurses —
            # nesting is legal and INNER levels override): shards
            # restrict the plan — in the TEXT, so every process
            # agrees — and the serialization flags ride the result
            opt_args.update(call.args)
            call = call.children[0]
        plan = self._plan(opt_args.get("shards"))
        result = self._dispatch(call, plan)
        if opt_args and hasattr(result, "segments"):
            result.exclude_columns = bool(opt_args.get("excludeColumns"))
            result.wants_column_attrs = bool(opt_args.get("columnAttrs"))
        return result

    def _dispatch(self, call, plan: Plan):
        if call.name in BITMAP_ROOTS:
            return self._bitmap_row(call, plan)
        if call.name == "Count":
            stack = self._eval_stack(call.children[0], plan)
            per_shard = np.asarray(_jit_count(plan.mesh)(stack),
                                   dtype=np.int64)
            return int(per_shard.sum())
        if call.name == "Sum":
            return self._sum(call, plan)
        if call.name in ("Min", "Max"):
            return self._extreme(call, plan)
        if call.name == "TopN":
            return self._topn(call, plan)
        if call.name == "GroupBy":
            return self._group_by(call, plan)
        if call.name == "Rows":
            return self._rows(call, plan)
        if call.name in ("MinRow", "MaxRow"):
            return self._extreme_row(call, plan)
        raise CollectiveError(call.name)

    def _field(self, name: str):
        f = self.idx.field(name)
        if f is None:
            raise CollectiveError(f"unknown field {name!r}")
        return f

    def _zero_stack(self, plan: Plan):
        import jax

        return jax.device_put(
            np.zeros((len(plan.order), bm.n_words(SHARD_WIDTH)),
                     np.uint32), _sharding(plan, 1))

    def _bitmap_row(self, call, plan: Plan):
        """Bare bitmap tree -> global Row, assembled host-side from
        replicated gathers (reference executeBitmapCall,
        executor.go:651; cross-node merge row.go Merge — here the
        merge IS the gather).

        Width bound: past MAX_ROW_GATHER_BYTES the tree is evaluated
        per shard-range SUB-PLAN — every call in a bare bitmap tree is
        shard-local (set algebra, BSI compares, time unions all work
        words-wise within a shard), so evaluating the tree restricted
        to a shard window yields exactly that window of the full
        result, each shard still evaluated once.  Both the sharded
        operand stacks and the replicated gather are then window-sized
        (a sliced gather of one big result stack would NOT bound
        memory: SPMD partitioning of a dynamic-slice on the sharded
        dim compiles to a full all-gather first).  Every process
        derives the identical window sequence from the shared plan —
        collective order safe."""
        from pilosa_tpu.models.row import Row

        segments: dict[int, np.ndarray] = {}

        def assemble(sub: Plan) -> None:
            stack = self._eval_stack(call, sub)
            full = np.asarray(_jit_gather(sub.mesh)(stack))
            for gi, s in enumerate(sub.order):
                if s >= 0 and full[gi].any():
                    # copy: a view would pin the whole gathered
                    # window for as long as one sparse segment lives
                    segments[s] = full[gi].copy()

        words = bm.n_words(SHARD_WIDTH)
        max_g = max(1, MAX_ROW_GATHER_BYTES // (words * 4))
        if len(plan.order) <= max_g:
            assemble(plan)
        else:
            real = [s for s in plan.order if s >= 0]
            owner = owner_rank_fn(self.cluster, self.index_name)
            for w0 in range(0, len(real), max_g):
                assemble(make_plan(real[w0:w0 + max_g], owner))
        return Row(segments)

    def _eval_stack(self, call, plan: Plan):
        name = call.name
        if name in ("Row", "Range"):
            if "from" in call.args or "to" in call.args:
                views = self._time_views(call)
                if views is None:
                    raise CollectiveError("time range not collectively "
                                          "evaluable")
                if not views:
                    return self._zero_stack(plan)
                fname = call.field_arg()
                return global_time_row_stack(
                    self._field(fname), call.args[fname],
                    tuple(views), plan)
            cond = call.condition_arg()
            if cond is not None:
                fname, condition = cond
                value = (condition.int_slice_value()
                         if condition.op == "><" else condition.value)
                return self._range_stack(self._field(fname),
                                         condition.op, value, plan)
            fname = call.field_arg()
            return global_row_stack(self._field(fname),
                                    call.args[fname], plan)
        if name == "Not":
            exist = global_row_stack(self.idx.existence_field(), 0, plan)
            return bm.b_andnot(exist,
                               self._eval_stack(call.children[0], plan))
        if name == "Shift":
            n = call.int_arg("n")
            return bm.b_shift(self._eval_stack(call.children[0], plan),
                              1 if n is None else n)
        kids = [self._eval_stack(c, plan) for c in call.children]
        op = {"Union": bm.b_or, "Intersect": bm.b_and,
              "Difference": bm.b_andnot, "Xor": bm.b_xor}[name]
        out = kids[0]
        for k in kids[1:]:
            out = op(out, k)
        return out

    def _range_stack(self, f, op: str, value, plan: Plan):
        rplan = f._classify_range(op, value)
        if rplan[0] == "empty":
            return self._zero_stack(plan)
        P = global_plane_stack(f, plan)
        if rplan[0] == "not_null":
            return _jit_exists(plan.mesh)(P)
        if rplan[0] == "between":
            return _jit_range_stack(plan.mesh, "between",
                                    rplan[1], rplan[2])(P)
        return _jit_range_stack(plan.mesh, rplan[1], rplan[2], 0)(P)

    def _sum(self, call, plan: Plan):
        from pilosa_tpu.parallel.results import ValCount

        fname = call.string_arg("field") or call.string_arg("_field")
        f = self._field(fname)
        P = global_plane_stack(f, plan)
        consider = _jit_exists(plan.mesh)(P)
        if call.children:
            consider = bm.b_and(consider,
                                self._eval_stack(call.children[0], plan))
        pos, neg, cnt = _jit_plane_counts(plan.mesh)(P, consider)
        pos = np.asarray(pos, dtype=np.int64).sum(axis=0)
        neg = np.asarray(neg, dtype=np.int64).sum(axis=0)
        total_count = int(np.asarray(cnt, dtype=np.int64).sum())
        total = sum((1 << i) * (int(p) - int(n))
                    for i, (p, n) in enumerate(zip(pos, neg)))
        return ValCount(total + total_count * f.options.base, total_count)

    def _extreme(self, call, plan: Plan):
        """Min/Max: one collective extremes scan, host sign-branching
        per shard + smaller/larger fold — the collective twin of the
        fused executor's _fused_extreme (same semantics, global mesh)."""
        from pilosa_tpu.parallel.results import ValCount

        fname = call.string_arg("field") or call.string_arg("_field")
        f = self._field(fname)
        P = global_plane_stack(f, plan)
        consider = _jit_exists(plan.mesh)(P)
        if call.children:
            consider = bm.b_and(consider,
                                self._eval_stack(call.children[0], plan))
        is_min = call.name == "Min"
        want = "min" if is_min else "max"
        (signed_cnt, all_cnt, primary_taken, fallback_taken,
         primary_n, fallback_n) = [
            np.asarray(x) for x in _jit_extremes(plan.mesh, want)(P, consider)]
        reducer = "smaller" if is_min else "larger"
        out = ValCount()
        for s in range(len(plan.order)):  # padding blocks count zero
            if all_cnt[s] == 0:
                continue
            if signed_cnt[s] > 0:
                v = bsi_ops.assemble_value(primary_taken[s])
                if is_min:
                    v = -v
                c = int(primary_n[s])
            else:
                v = bsi_ops.assemble_value(fallback_taken[s])
                if not is_min:
                    v = -v  # Max of all-negative = closest to zero
                c = int(fallback_n[s])
            out = getattr(out, reducer)(ValCount(v + f.options.base, c))
        return out

    #: ceiling on the cartesian product of OUTER levels for a
    #: >=3-child GroupBy (one filtered pair-counts dispatch per
    #: combination); wider outer spaces decline to the scatter path
    #: rather than queue hundreds of device programs
    MAX_OUTER_DISPATCHES = 64

    def _restrict_agreed_ids(self, f, call, ids, plan: Plan,
                             cover) -> list[int]:
        """The executor's Rows constraint order over an agreed list —
        column bit filter (one tiny collective; ceiling-guarded: the
        [G, R] gather is the only dense operand here), then previous,
        then limit (reference executeRows push-down,
        executor.go:1040-1071).  Shared by standalone Rows and the
        GroupBy constrained-child pre-selection so the lockstep-
        critical logic cannot drift between them."""
        colarg = call.uint_arg("column")
        if colarg is not None and ids:
            if len(ids) > MAX_COLLECTIVE_ROWS:
                raise CollectiveError(
                    f"column filter over {len(ids)} rows exceeds the "
                    f"dense collective ceiling {MAX_COLLECTIVE_ROWS}")
            bitvec = global_column_bits(f, ids, colarg, plan, cover)
            ids = [r for r, bit in zip(ids, bitvec) if bit]
        prev = call.uint_arg("previous")
        if prev is not None:
            ids = [r for r in ids if r > prev]
        lim = call.uint_arg("limit")
        if lim is not None:
            ids = ids[:lim]
        return ids

    def _rows(self, call, plan: Plan) -> list[int]:
        """Standalone Rows: the agreed global row-id list over the
        call's view cover, with the executor's constraint order
        (reference executeRows, executor.go:1040-1071; scatter analog
        _execute_rows)."""
        fname = call.args.get("_field") or call.args.get("field")
        f = self._field(fname)
        views = self._rows_views(f, call)
        if views is None:
            raise CollectiveError(f"Rows({fname}) time cover not "
                                  f"collectively evaluable")
        if not views:
            return []
        cover = tuple(views)
        return self._restrict_agreed_ids(f, call,
                                         agreed_row_ids(f, cover, plan_shards(plan)),
                                         plan, cover)

    def _extreme_row(self, call, plan: Plan):
        """MinRow/MaxRow: the smallest/largest row id with any bit
        (optionally intersected with a filter), plus its count — one
        collective row-counts scan over the agreed list (reference
        executeMinRow/executeMaxRow, executor.go:3029)."""
        from pilosa_tpu.parallel.results import Pair

        fname = call.string_arg("field") or call.args.get("field")
        f = self._field(fname)
        ids = agreed_row_ids(f, shards=plan_shards(plan))
        if not ids:
            return Pair()
        if len(ids) > MAX_COLLECTIVE_ROWS:
            raise CollectiveError(
                f"{call.name} over {len(ids)} rows exceeds the dense "
                f"collective ceiling {MAX_COLLECTIVE_ROWS}")
        mat = global_matrix_stack(f, ids, plan)
        if call.children:
            filt = self._eval_stack(call.children[0], plan)
            per_shard = _jit_row_counts(plan.mesh, True)(mat, filt)
        else:
            per_shard = _jit_row_counts(plan.mesh, False)(mat)
        counts = np.asarray(per_shard, dtype=np.int64).sum(axis=0)
        live = [(r, int(c)) for r, c in zip(ids, counts) if c > 0]
        if not live:
            return Pair()
        rid, cnt = min(live) if call.name == "MinRow" else max(live)
        return Pair(id=rid, count=cnt)

    def _group_by(self, call, plan: Plan):
        """GroupBy over N Rows children: agreed row-id lists per child
        (over each child's view cover — time-constrained children scan
        their covering time views), collective cartesian-counts
        programs, host assembly in the executor's sorted-group order
        with offset-then-limit (executor.go:1135-1149).  Three or more
        children run as a lockstep loop over the outer levels'
        cartesian product — one filtered pair-counts program per outer
        combination, every process iterating the identical product
        (reference analog: the groupByIterator's cartesian walk,
        executor.go:3058)."""
        import itertools
        import math

        from pilosa_tpu.parallel.results import FieldRow, GroupCount

        fields = []
        row_lists = []
        for child in call.children:
            fname = child.args.get("_field") or child.args.get("field")
            f = self._field(fname)
            if f.options.no_standard_view:
                # the reference's iterator needs the standard fragment
                # and bails per shard without it (newGroupByIterator,
                # executor.go:3101-3104) — the whole GroupBy is empty
                return []
            # row pre-SELECTION cover: constrained children select
            # over their time cover like the scatter pre-executed Rows
            # query; unconstrained children list standard-view rows
            # (from/to is ignored there, as the reference does).
            # Counts below always come from viewStandard.
            if self._child_constrained(child):
                views = self._child_selection_views(child)
                if views is None:
                    raise CollectiveError(
                        f"Rows({fname}) time cover not collectively "
                        f"evaluable")
                if not views:
                    return []  # provably empty time range
                sel_cover = tuple(views)
            else:
                sel_cover = (VIEW_STANDARD,)
            ids = agreed_row_ids(f, sel_cover, plan_shards(plan))
            if len(ids) > MAX_COLLECTIVE_ROWS:
                raise CollectiveError(
                    f"field {fname!r} has {len(ids)} rows > "
                    f"{MAX_COLLECTIVE_ROWS}; dense collective GroupBy "
                    f"declines (scatter path's level walk handles it)")
            # constrained children restrict in the executor's order
            # (shared helper: column gather replicates, previous/limit
            # are pure functions of the agreed list — every process
            # derives the identical restricted list, lockstep holds)
            ids = self._restrict_agreed_ids(f, child, ids, plan,
                                            sel_cover)
            if not ids:
                return []
            fields.append(f)
            row_lists.append(ids)
        if (len(row_lists) >= 2 and
                math.prod(len(l) for l in row_lists)
                > MAX_COLLECTIVE_PAIRS):
            # the TOTAL group space is what the host accumulates —
            # bounding only the inner pair space would admit
            # outer x pairs far past the 2-child ceiling
            raise CollectiveError("GroupBy group space too large for "
                                  "the dense collective path")
        if (len(row_lists) >= 3 and
                math.prod(len(l) for l in row_lists[:-2])
                > self.MAX_OUTER_DISPATCHES):
            raise CollectiveError(
                f"GroupBy outer levels span more than "
                f"{self.MAX_OUTER_DISPATCHES} combinations; scatter "
                f"path walks them")
        filt_call = call.call_arg("filter")
        filt = (self._eval_stack(filt_call, plan)
                if filt_call is not None else None)
        if len(fields) == 1:
            mat = global_matrix_stack(fields[0], row_lists[0], plan)
            if filt is not None:
                per_shard = _jit_row_counts(plan.mesh, True)(mat, filt)
            else:
                per_shard = _jit_row_counts(plan.mesh, False)(mat)
            counts = np.asarray(per_shard, dtype=np.int64).sum(axis=0)
            totals = {((fields[0].name, r),): int(c)
                      for r, c in zip(row_lists[0], counts) if c > 0}
        elif len(fields) == 2:
            mat_a = global_matrix_stack(fields[0], row_lists[0], plan)
            mat_b = global_matrix_stack(fields[1], row_lists[1], plan)
            if filt is not None:
                per_shard = _jit_pair_counts(plan.mesh, True)(
                    mat_a, mat_b, filt)
            else:
                per_shard = _jit_pair_counts(plan.mesh, False)(mat_a, mat_b)
            counts = np.asarray(per_shard, dtype=np.int64).sum(axis=0)
            ra_ids = np.asarray(row_lists[0])
            rb_ids = np.asarray(row_lists[1])
            totals = {}
            for i, j in np.argwhere(counts > 0):
                totals[((fields[0].name, int(ra_ids[i])),
                        (fields[1].name, int(rb_ids[j])))] = \
                    int(counts[i, j])
        else:
            mat_b = global_matrix_stack(fields[-2], row_lists[-2], plan)
            mat_c = global_matrix_stack(fields[-1], row_lists[-1], plan)
            rb_ids = np.asarray(row_lists[-2])
            rc_ids = np.asarray(row_lists[-1])
            totals = {}
            for combo in itertools.product(*row_lists[:-2]):
                fa = None
                for f_o, rid in zip(fields[:-2], combo):
                    stack = global_row_stack(f_o, rid, plan)
                    fa = stack if fa is None else bm.b_and(fa, stack)
                if filt is not None:
                    fa = bm.b_and(fa, filt)
                per_shard = _jit_pair_counts(plan.mesh, True)(
                    mat_b, mat_c, fa)
                counts = np.asarray(per_shard, dtype=np.int64).sum(axis=0)
                prefix = tuple((f_o.name, rid) for f_o, rid
                               in zip(fields[:-2], combo))
                for j, k in np.argwhere(counts > 0):
                    totals[prefix
                           + ((fields[-2].name, int(rb_ids[j])),
                              (fields[-1].name, int(rc_ids[k])))] = \
                        int(counts[j, k])
        out = [GroupCount(group=[FieldRow(field=fn, row_id=r)
                                 for fn, r in key], count=c)
               for key, c in sorted(totals.items())]
        offset = call.uint_arg("offset")
        if offset is not None:
            out = out[offset:] if offset < len(out) else out
        limit = call.uint_arg("limit")
        if limit is not None:
            out = out[:limit]
        return out

    def _topn(self, call, plan: Plan):
        from pilosa_tpu.parallel.results import Pair

        fname = call.string_arg("_field") or call.args.get("_field")
        f = self._field(fname)
        n = call.uint_arg("n") or 0
        ids_arg = call.uint_slice_arg("ids")
        threshold = call.uint_arg("threshold") or 0
        tanimoto = call.uint_arg("tanimotoThreshold") or 0
        row_ids = agreed_row_ids(f, shards=plan_shards(plan))
        if not row_ids:
            return []
        if len(row_ids) > MAX_COLLECTIVE_ROWS:
            raise CollectiveError(
                f"TopN over {len(row_ids)} rows exceeds the dense "
                f"collective ceiling {MAX_COLLECTIVE_ROWS}")
        mat = global_matrix_stack(f, row_ids, plan)
        filt = (self._eval_stack(call.children[0], plan)
                if call.children else None)
        if filt is not None:
            per_shard = _jit_row_counts(plan.mesh, True)(mat, filt)
        else:
            per_shard = _jit_row_counts(plan.mesh, False)(mat)
        counts = np.asarray(per_shard, dtype=np.int64).sum(axis=0)
        totals = {rid: int(c) for rid, c in zip(row_ids, counts) if c > 0}

        # post-count filters, in the executor's exact order
        # (executor.py _execute_topn; reference executor.go:860-1038)
        if ids_arg:
            allowed = set(ids_arg)
            totals = {r: c for r, c in totals.items() if r in allowed}
        attr_name = call.string_arg("attrName")
        if attr_name:
            # attrs filter host-side AFTER the identical device
            # dispatches, so SPMD lockstep holds; stores are AE-synced,
            # and only the coordinator's host answer reaches the client
            attr_values = call.args.get("attrValues")
            if not isinstance(attr_values, list):
                raise CollectiveError("TopN() attrValues must be a list")
            allowed_vals = set(attr_values)
            row_attrs = f.row_attrs.attrs_bulk(totals)
            totals = {
                r: c for r, c in totals.items()
                if row_attrs.get(r, {}).get(attr_name) in allowed_vals
            }
        if tanimoto and filt is not None:
            # same math as the scatter path: count pre-window on FULL
            # row counts, then the exact coefficient on global counts
            # (two more collective dispatches — src popcount and the
            # unfiltered scan — identical programs on every process)
            import math

            src_count = int(np.asarray(_jit_count(plan.mesh)(filt),
                                       dtype=np.int64).sum())
            full = np.asarray(_jit_row_counts(plan.mesh, False)(mat),
                              dtype=np.int64).sum(axis=0)
            full_counts = {rid: int(c) for rid, c in zip(row_ids, full)}
            lo = src_count * tanimoto / 100.0
            hi = src_count * 100.0 / tanimoto
            kept = {}
            for r, inter in totals.items():
                cnt = full_counts.get(r, 0)
                if not (lo < cnt < hi) or inter == 0:
                    continue
                coeff = math.ceil(inter * 100.0
                                  / (cnt + src_count - inter))
                if coeff > tanimoto:
                    kept[r] = inter
            totals = kept
        elif threshold:
            totals = {r: c for r, c in totals.items() if c >= threshold}

        pairs = [Pair(id=r, count=c) for r, c in totals.items()]
        pairs.sort(key=lambda p: (-p.count, p.id))
        return pairs[: n] if n else pairs
