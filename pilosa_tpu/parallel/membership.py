"""Membership liveness: SWIM-style failure detection over the control
plane.

Parity target: the reference's gossip/SWIM membership
(gossip/gossip.go:43-612, hashicorp memberlist delegate) and its
false-down protection — a suspect node is dialed repeatedly before
being declared DOWN (cluster.go:1724 confirmNodeDown, 10 retries).
The TPU-native design keeps the request/response DCN control plane
(no UDP) but adopts SWIM's scalable shape (round 4, VERDICT #5):

- **k-random probing**: each round a node probes ``PROBE_FANOUT``
  random peers, not every peer — cluster-wide load is O(N·k) messages
  per round instead of the previous serial O(N²) sweep.
- **Concurrent probes with a deadline**: the round's pings run on
  worker threads and the round waits at most ``PROBE_DEADLINE_S`` —
  one slow peer no longer stretches every node's detection latency,
  and confirm-down retries run inside the suspect's own worker rather
  than blocking the sweep inline.
- **Indirect probing** (SWIM ping-req): a failed direct probe asks
  ``INDIRECT_PROBES`` other peers to dial the suspect before any
  confirm round — a broken prober↔suspect link does not produce a
  false DOWN.
- **Piggybacked dissemination**: pings carry the prober's node-state
  view and responses carry the responder's; DISAGREEMENTS become
  next-round probe hints, never blind state writes (a stale gossiped
  DOWN cannot flap a healthy node — every state change still goes
  through this node's own confirm machinery).  Confirmed changes
  broadcast as ``node-state`` messages exactly as before.

Query-time replica failover (executor mapReduce re-mapping,
executor.go:2492) is independent of this detector — it handles
mid-query loss; the detector handles steady-state routing (DOWN
primaries are skipped up front in shards_by_node)."""

from __future__ import annotations

import os
import random as _random
import threading
import time

from pilosa_tpu.parallel.cluster import (
    NODE_DOWN,
    NODE_READY,
    ShedByPeerError,
    TransportError,
)

#: direct probes per round (SWIM k); every peer is still probed when
#: the cluster is smaller than k, so small clusters detect in 1 round
PROBE_FANOUT = 3

#: peers asked to dial a suspect on our behalf after a failed direct
#: probe (SWIM ping-req fan-out)
INDIRECT_PROBES = 2

#: wall-clock bound on one round's concurrent probe phase.  Env-
#: overridable so process-level tests can tighten detection latency
#: to fit their wait windows deterministically under CI load.
PROBE_DEADLINE_S = float(
    os.environ.get("PILOSA_TPU_PROBE_DEADLINE_S", "5.0"))

# Dial attempts before declaring a node DOWN (cluster.go:1724 uses 10
# ×1s; the control plane here is request/response so 3 suffices).
CONFIRM_RETRIES = 3


def _probe_alive_hint(e: Exception) -> bool | None:
    """Shared liveness classification for probe exceptions: True =
    the peer ANSWERED over HTTP (any status — shed 429/503, even a
    500 mid-rolling-upgrade) and is therefore alive; False = the
    probe was inconclusive (the client's own deadline spent); None =
    a programming error that must propagate loudly, never silently
    become a DOWN marking."""
    from pilosa_tpu.serve.deadline import DeadlineExceededError
    from pilosa_tpu.server.client import ClientError

    if isinstance(e, ClientError):
        return True
    if isinstance(e, DeadlineExceededError):
        return False
    return None


def _send(transport, target, msg, timeout=None):
    """Transport send with an optional per-dial bound.  Feature-
    detected (``send_message_timeout`` on HTTPTransport): test fabrics
    and wrappers that only implement ``send_message`` keep working."""
    f = getattr(transport, "send_message_timeout", None)
    if f is not None and timeout is not None:
        return f(target, msg, timeout)
    return transport.send_message(target, msg)


def ping(node, target, timeout: float | None = None) -> bool:
    ok, _ = ping_with_states(node, target, piggyback=False,
                             timeout=timeout)
    return ok


def ping_with_states(node, target, piggyback: bool = True,
                     timeout: float | None = None):
    """-> (alive, responder_node_states | None).  With ``piggyback``
    the request carries our state view so the responder can hint-check
    disagreements on its next round."""
    msg: dict = {"type": "ping"}
    if piggyback:
        msg["states"] = {n.id: n.state
                        for n in node.cluster.sorted_nodes()}
    try:
        resp = _send(node.cluster.transport, target, msg, timeout)
        return bool(resp.get("ok")), resp.get("node_states")
    except ShedByPeerError:
        # An admission-shed probe (429/503 from the peer's gate) is
        # PROOF OF LIFE: the peer answered.  Overload must never read
        # as death, or load shedding would amplify into false DOWN
        # markings and resize churn.  Checked BEFORE TransportError —
        # it subclasses it so fan-outs can skip shed peers.
        return True, None
    except TransportError:
        return False, None
    except Exception as e:
        alive = _probe_alive_hint(e)
        if alive is None:
            raise
        return alive, None


def indirect_probe(node, target, peers, rng,
                   n_relays: int = INDIRECT_PROBES,
                   timeout: float | None = None) -> bool:
    """SWIM ping-req: ask up to ``n_relays`` other live peers to dial
    the suspect; True if any relay reaches it."""
    relays = [p for p in peers
              if p.id != target.id and p.state != NODE_DOWN]
    for relay in rng.sample(relays, min(n_relays, len(relays))):
        try:
            resp = _send(node.cluster.transport, relay,
                         {"type": "ping-req", "target": target.id},
                         timeout)
            if resp.get("ok") and resp.get("alive"):
                return True
        except TransportError:
            continue
        except Exception as e:
            # a relay that ANSWERED (even with a shed/error status)
            # could not vouch for the target — try the next relay;
            # programming errors propagate (_probe_alive_hint None)
            if _probe_alive_hint(e) is None:
                raise
            continue
    return False


def confirm_down(node, target, timeout: float | None = None) -> bool:
    """True if the target is really unreachable after retries
    (cluster.go:1724 confirmNodeDown)."""
    for _ in range(CONFIRM_RETRIES):
        if ping(node, target, timeout=timeout):
            return False
    return True


#: guards every node's hint set: the bus ping handler adds hints on a
#: transport thread while the heartbeat loop pops them — an
#: unsynchronized swap would orphan a concurrent add's whole batch
_hints_lock = threading.Lock()


def take_hints(node) -> set:
    """Pop the node ids queued for a priority probe (piggybacked
    disagreements recorded by the bus ping handler or a prior round)."""
    with _hints_lock:
        hints = getattr(node, "_membership_hints", set())
        node._membership_hints = set()
        return hints


def add_hints(node, node_ids) -> None:
    with _hints_lock:
        hints = getattr(node, "_membership_hints", None)
        if hints is None:
            hints = node._membership_hints = set()
        hints.update(node_ids)


def heartbeat_round(node, k: int = PROBE_FANOUT,
                    rng=None,
                    deadline_s: float = PROBE_DEADLINE_S) -> dict[str, str]:
    """One SWIM round: k random peers (plus any hinted suspects) probed
    CONCURRENTLY under one deadline; failed probes escalate through
    indirect ping-req, then confirm-down; confirmed changes apply
    locally and broadcast (reference: memberlist events ->
    cluster.ReceiveEvent, cluster.go:1754).  Returns {node_id:
    new_state} for nodes whose state changed."""
    cluster = node.cluster
    if cluster.transport is None:
        return {}
    rng = rng or _random
    peers = [p for p in cluster.sorted_nodes()
             if p.id != cluster.local_id]
    if not peers:
        return {}
    # probe set: hinted disagreements first (they were gossiped —
    # verify them ourselves), then k random peers.  take_hints pops
    # the set ONCE — calling it per element would empty it mid-scan
    hinted = take_hints(node)
    targets = {p.id: p for p in peers if p.id in hinted}
    pool = [p for p in peers if p.id not in targets]
    if pool:
        for p in rng.sample(pool, min(k, len(pool))):
            targets[p.id] = p

    # round-private state, guarded: an abandoned straggler thread can
    # finish its confirm while the round thread snapshots — unguarded,
    # the dict/set copy races a concurrent resize
    round_lock = threading.Lock()
    results: dict[str, str] = {}
    gossip_hints: set[str] = set()
    done: set[str] = set()

    def probe(target) -> None:
        try:
            _probe(target)
        except Exception:  # noqa: BLE001 — a probe thread must never
            # surface an exception: abandoned stragglers can run past
            # the round (even past test teardown); any failure simply
            # means no result for this round
            pass

    # per-dial budget: the worst escalation chain is 1 direct + 2
    # indirect + 3 confirm = 6 sequential dials, and a dead host that
    # swallows packets costs a full timeout per dial — the chain must
    # finish INSIDE the round deadline or the confirm result would be
    # dropped every round and the node never marked DOWN
    per_dial = max(0.2, deadline_s / 8.0)

    def _probe(target) -> None:
        alive, their_states = ping_with_states(node, target,
                                               timeout=per_dial)
        if their_states:
            hint = {nid for nid, st in their_states.items()
                    if nid != cluster.local_id
                    and (known := cluster.node(nid)) is not None
                    and known.state != st}
            if hint:
                with round_lock:
                    gossip_hints.update(hint)
        if not alive:
            alive = indirect_probe(node, target, peers, rng,
                                   timeout=per_dial)
        # circuit-breaker half-open trials ride the heartbeat: a
        # successful probe closes the peer's open breaker without
        # waiting for query traffic to gamble on it (a failed probe of
        # a CLOSED breaker is deliberately NOT fed — one lost ping
        # must not open breakers; see Cluster.note_probe)
        note_probe = getattr(cluster, "note_probe", None)
        if note_probe is not None:
            note_probe(target.id, alive)
        change = None
        if not alive and target.state != NODE_DOWN:
            if confirm_down(node, target, timeout=per_dial):
                change = NODE_DOWN
        elif alive and target.state == NODE_DOWN:
            change = NODE_READY
        with round_lock:
            if change is not None:
                results[target.id] = change
            done.add(target.id)

    threads = [threading.Thread(target=probe, args=(t,), daemon=True)
               for t in targets.values()]
    for t in threads:
        t.start()
    deadline = time.monotonic() + deadline_s
    for t in threads:
        t.join(timeout=max(0.0, deadline - time.monotonic()))
    # stragglers past the deadline are abandoned (daemon threads); a
    # late result for THIS round is simply dropped — the next round
    # re-probes.  Changes apply on the round's thread only.
    with round_lock:
        changes = dict(results)
        pending = set(gossip_hints)
    # hinted suspects whose probe was abandoned keep their priority:
    # re-queue them so the next round re-probes first.  Restricted to
    # CURRENT peers — a hint naming a node a resize removed would
    # otherwise re-queue forever (it can never be probed or done)
    peer_ids = {p.id for p in peers}
    add_hints(node,
              ((pending | (hinted - done)) - set(changes)) & peer_ids)
    for nid, state in changes.items():
        cluster.set_node_state(nid, state)
        node.broadcast({"type": "node-state", "node": nid, "state": state})
    return changes
