"""Membership liveness: heartbeat-based failure detection.

Parity target: the reference's gossip/SWIM membership (gossip/gossip.go
memberlist delegate) and its false-down protection — a suspect node is
dialed repeatedly before being declared DOWN (cluster.go:1724
confirmNodeDown, 10 retries).  The TPU-native design replaces UDP gossip
with direct heartbeats over the DCN control plane: every node pings its
peers each round; state changes broadcast as node-state messages and the
NORMAL/DEGRADED state machine reacts (cluster.go:571-583).

Query-time replica failover (executor mapReduce re-mapping,
executor.go:2492) is independent of this detector — it handles mid-query
loss; the detector handles steady-state routing (DOWN primaries are
skipped up front in shards_by_node)."""

from __future__ import annotations

from pilosa_tpu.parallel.cluster import (
    NODE_DOWN,
    NODE_READY,
    TransportError,
)

# Dial attempts before declaring a node DOWN (cluster.go:1724 uses 10
#×1s; the control plane here is request/response so 3 suffices).
CONFIRM_RETRIES = 3


def ping(node, target) -> bool:
    try:
        resp = node.cluster.transport.send_message(target, {"type": "ping"})
        return bool(resp.get("ok"))
    except TransportError:
        return False


def confirm_down(node, target) -> bool:
    """True if the target is really unreachable after retries
    (cluster.go:1724 confirmNodeDown)."""
    for _ in range(CONFIRM_RETRIES):
        if ping(node, target):
            return False
    return True


def heartbeat_round(node) -> dict[str, str]:
    """One liveness sweep over all peers; returns {node_id: new_state}
    for nodes whose state changed.  State changes are applied locally
    and broadcast (reference: memberlist events -> cluster.ReceiveEvent,
    cluster.go:1754)."""
    cluster = node.cluster
    if cluster.transport is None:
        return {}
    changes: dict[str, str] = {}
    for target in cluster.sorted_nodes():
        if target.id == cluster.local_id:
            continue
        alive = ping(node, target)
        if not alive and target.state != NODE_DOWN:
            if confirm_down(node, target):
                changes[target.id] = NODE_DOWN
        elif alive and target.state == NODE_DOWN:
            changes[target.id] = NODE_READY
    for nid, state in changes.items():
        cluster.set_node_state(nid, state)
        node.broadcast({"type": "node-state", "node": nid, "state": state})
    return changes
