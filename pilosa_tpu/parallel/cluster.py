"""Cluster layer: shard placement, membership state, write replication.

Parity target: the reference's cluster (cluster.go).  The placement
scheme is kept bit-compatible so operational expectations transfer
(SURVEY.md §7 step 5):

- ``partition(index, shard) = fnv64a(index || shard_le8) % partition_n``
  (cluster.go:871, defaultPartitionN=256 cluster.go:44)
- partition -> primary node via **jump consistent hash** over the sorted
  node list (cluster.go:948-959)
- replicas = the next ``replica_n - 1`` nodes on the sorted ring
  (cluster.go:902-924)

The communication fabric is pluggable (``Transport``): in-process for
tests (the reference's DisableCluster/static mode, cluster.go:2037), HTTP
for real deployments, with the mesh/ICI path fusing whole local shard
batches on device (pilosa_tpu.parallel.mesh).  State machine and node
states mirror cluster.go:46-58.
"""

from __future__ import annotations

import json
import os
import threading
import time as _time
from dataclasses import dataclass

# Cluster states (cluster.go:46-50)
STATE_STARTING = "STARTING"
STATE_NORMAL = "NORMAL"
STATE_DEGRADED = "DEGRADED"
STATE_RESIZING = "RESIZING"

# Node states (cluster.go:52-58)
NODE_READY = "READY"
NODE_DOWN = "DOWN"

DEFAULT_PARTITION_N = 256


def fnv64a(data: bytes) -> int:
    """FNV-1a 64-bit (hash/fnv used at cluster.go:873)."""
    h = 0xCBF29CE484222325
    for b in data:
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


def partition(index: str, shard: int, partition_n: int = DEFAULT_PARTITION_N) -> int:
    """Shard -> partition (cluster.go:871): hash of index name and the
    shard id's little-endian 8 bytes."""
    return fnv64a(index.encode() + shard.to_bytes(8, "little")) % partition_n


def jump_hash(key: int, n_buckets: int) -> int:
    """Jump consistent hash (Lamping & Veach; cluster.go:948 jmphasher).
    Maps key uniformly onto [0, n_buckets) with minimal movement as
    buckets are added/removed."""
    b, j = -1, 0
    key &= 0xFFFFFFFFFFFFFFFF
    while j < n_buckets:
        b = j
        key = (key * 2862933555777941757 + 1) & 0xFFFFFFFFFFFFFFFF
        j = int((b + 1) * (float(1 << 31) / float((key >> 33) + 1)))
    return b


def shard_owners(sorted_node_ids: list[str], index: str, shard: int,
                 replica_n: int, partition_n: int = DEFAULT_PARTITION_N,
                 hasher=None) -> list[str]:
    """Owner node ids of a shard under a hypothetical membership —
    placement math detached from a live Cluster, used by resize planning
    to diff old-vs-new topologies (cluster.go:726 fragCombos)."""
    if not sorted_node_ids:
        return []
    hash_fn = (hasher or JmpHasher()).hash
    p = partition(index, shard, partition_n)
    start = hash_fn(p, len(sorted_node_ids))
    k = min(replica_n, len(sorted_node_ids))
    return [sorted_node_ids[(start + i) % len(sorted_node_ids)]
            for i in range(k)]


class ModHasher:
    """Deterministic partition->node hasher for tests (test/cluster.go:18)."""

    @staticmethod
    def hash(key: int, n: int) -> int:
        return key % n


class JmpHasher:
    @staticmethod
    def hash(key: int, n: int) -> int:
        return jump_hash(key, n)


@dataclass
class Node:
    """One cluster member (pilosa.Node)."""

    id: str
    uri: str = ""
    is_coordinator: bool = False
    state: str = NODE_READY

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "uri": self.uri,
            "isCoordinator": self.is_coordinator,
            "state": self.state,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Node":
        return cls(
            id=d["id"],
            uri=d.get("uri", ""),
            is_coordinator=d.get("isCoordinator", False),
            state=d.get("state", NODE_READY),
        )


class TransportError(RuntimeError):
    """A node could not be reached or failed mid-request; triggers
    replica failover in the executor (executor.go:2492)."""


class ShedByPeerError(TransportError):
    """The peer's admission gate refused the request (429/503 with
    Retry-After, serve/admission.py) and the client's shed retries are
    exhausted.  Subclasses TransportError on purpose: best-effort
    fan-outs — broadcast, anti-entropy peer loops, resize source
    fallback, the executor's replica failover — must SKIP an
    overloaded peer exactly like an unreachable one (a later sweep or
    another replica picks it up).  Liveness checks must test for this
    FIRST: a shed response is proof of life, never evidence of death
    (parallel/membership.py)."""

    def __init__(self, msg: str, status: int):
        super().__init__(msg)
        self.status = status


#: cross-transport marker for a replica write delivery refused by a
#: non-owner (reference api.go ErrClusterDoesNotOwnShard).  Typed
#: exceptions survive LocalTransport and carry a structured
#: ``.unowned`` flag; over HTTP the refusal travels as an error STRING,
#: so the origin falls back to matching this token — DISTINCTIVE by
#: construction (the reference's error name, which no organic error
#: text contains), so an unrelated failure that merely mentions shards
#: cannot be misread as a refusal and silently converted into the
#: 10 s convergence-retry loop.
UNOWNED_MARKER = "ErrClusterDoesNotOwnShard"


def refusal_is_unowned(exc: BaseException) -> bool:
    return bool(getattr(exc, "unowned", False)) or UNOWNED_MARKER in str(exc)


def converge_owner_deliveries(delivery_pass, on_timeout) -> None:
    """Drive ``delivery_pass()`` (one sweep over the CURRENT owner
    set; returns True when some owner refused as non-owner) until no
    refusals remain — an owner refusing means its membership view is
    fresher than ours, so wait for the status broadcast and
    re-resolve.  Shared by the import fan-out (api._send_to_owners)
    and the PQL write replication (executor._replicate_to_shard_owners)
    so the budget/backoff semantics cannot drift between them.  On
    budget exhaustion calls ``on_timeout()`` (which raises the
    caller's error type)."""
    import os
    import time

    budget = float(os.environ.get("PILOSA_TPU_WRITE_RETRY_S", "10.0"))
    deadline = time.monotonic() + budget
    while True:
        if not delivery_pass():
            return
        if time.monotonic() >= deadline:
            on_timeout()
            return
        time.sleep(0.2)


def fan_in(nodes: list, fetch, timeout: float) -> tuple[dict, dict]:
    """Best-effort concurrent fan-out: run ``fetch(node)`` for every
    node on its own thread, bounded by ``timeout`` seconds overall.
    Returns ``(results, errors)`` keyed by node id — a node that errors
    or misses the window lands in ``errors`` instead of failing the
    whole merge.  The cluster-wide debug surfaces
    (``/debug/cluster/*``) ride this: one slow or dead peer must cost
    its own section, never the operator's merged view."""
    import time

    from pilosa_tpu import tracing as _tracing

    results: dict = {}
    errors: dict = {}
    lock = threading.Lock()
    # fan-in worker threads re-attach the caller's trace so the peer
    # fetches carry traceparent (a /debug/trace fan-in is itself part
    # of the trace's causal record)
    tid = _tracing.active_trace_id()

    def run(node):
        try:
            with _tracing.propagate(tid):
                out = fetch(node)
            with lock:
                results[node.id] = out
        except Exception as e:  # noqa: BLE001 — per-node best effort
            with lock:
                errors[node.id] = f"{type(e).__name__}: {e}"

    threads = [threading.Thread(target=run, args=(n,), daemon=True)
               for n in nodes]
    for t in threads:
        t.start()
    deadline = time.monotonic() + timeout
    for t in threads:
        t.join(max(0.0, deadline - time.monotonic()))
    with lock:
        for node in nodes:
            if node.id not in results and node.id not in errors:
                errors[node.id] = f"timeout after {timeout:g}s"
        return dict(results), dict(errors)


class Transport:
    """Node-to-node fabric (the reference's InternalClient role,
    http/client.go:37)."""

    def query_node(self, node: Node, index: str, pql: str, shards: list[int],
                   nocache: bool = False, nodelta: bool = False,
                   nocontainers: bool = False, nomesh: bool = False,
                   notiers: bool = False, novm: bool = False,
                   partial: bool = False,
                   tenant: str | None = None):
        """Execute pql on the remote node restricted to `shards` with
        remote semantics (no re-translation).  Returns the result list.
        Raises TransportError if the node is unreachable.  ``nocache``
        forwards the origin request's ?nocache=1 so an opted-out query
        forces a real execution on every node, not just the origin;
        ``nodelta`` forwards ?nodelta=1 the same way (peers compact
        their pending ingest deltas and answer from pure base);
        ``nocontainers`` forwards ?nocontainers=1 (peers route their
        fused reads through the dense pre-container path); ``nomesh``
        forwards ?nomesh=1 (peers run their fused dispatches on the
        pre-mesh single-device programs); ``notiers``
        forwards ?notiers=1 (peers bypass their tiered residency:
        inline rebuilds, drop-not-demote); ``novm`` forwards ?novm=1
        (peers route their coalesced sparse reads through the pre-VM
        engines); ``partial``
        forwards ?partial=1 (degraded-read semantics ride sub-queries
        like the other per-request escapes); ``tenant`` forwards the
        origin's tenant id as ?tenant= (the peer's admission gate,
        result cache and residency tiers charge the same tenant)."""
        raise NotImplementedError

    def send_message(self, node: Node, message: dict) -> dict:
        """Control-plane RPC (schema DDL, cluster status, resize...)."""
        raise NotImplementedError


class LocalTransport(Transport):
    """In-process fabric for multi-node tests: the registry maps node id
    -> handle with .executor/.holder/.receive_message (the reference's
    in-process test cluster, test/pilosa.go:390).

    Fault injection: ``set_down`` makes a node unreachable from
    everyone (process death); ``set_partition(a, b)`` drops messages
    between a PAIR of live nodes bidirectionally (the pumba netem
    partition, internal/clustertests/cluster_test.go:69-80) — each
    side still serves everyone else, so SWIM indirect probing through
    a third node can still vouch for both.  Partition enforcement
    needs the sender's identity, which the wire protocol has but a
    shared in-process registry does not — ``bind(node_id)`` returns a
    per-node view that stamps the sender on every call."""

    def __init__(self):
        self.handles: dict[str, object] = {}
        self.down: set[str] = set()
        self.partitions: set[frozenset] = set()
        self.slow: dict[str, float] = {}

    def register(self, node_id: str, handle) -> None:
        self.handles[node_id] = handle

    def set_down(self, node_id: str, down: bool = True) -> None:
        (self.down.add if down else self.down.discard)(node_id)

    def set_slow(self, node_id: str, delay_s: float = 0.0) -> None:
        """Gray failure: the node stays alive and correct but every
        message to it is delayed — distinct from death (no
        TransportError, so no failover) and from partition (everyone
        is affected equally).  SWIM must keep it a member; reads and
        writes must stay exact, just slower."""
        if delay_s > 0:
            self.slow[node_id] = delay_s
        else:
            self.slow.pop(node_id, None)

    def _maybe_delay(self, node_id: str) -> None:
        d = self.slow.get(node_id)
        if d:
            import time

            time.sleep(d)

    def set_partition(self, a: str, b: str, on: bool = True) -> None:
        key = frozenset((a, b))
        (self.partitions.add if on else self.partitions.discard)(key)

    def bind(self, node_id: str) -> "BoundTransport":
        return BoundTransport(self, node_id)

    def _check_partition(self, src: str, dst: str) -> None:
        if frozenset((src, dst)) in self.partitions:
            raise TransportError(f"partitioned: {src} <-/-> {dst}")

    def query_node(self, node: Node, index: str, pql: str, shards: list[int],
                   nocache: bool = False, nodelta: bool = False,
                   nocontainers: bool = False, nomesh: bool = False,
                   notiers: bool = False, novm: bool = False,
                   partial: bool = False,
                   tenant: str | None = None):
        from pilosa_tpu.parallel.executor import ExecOptions

        if node.id in self.down or node.id not in self.handles:
            raise TransportError(f"node unreachable: {node.id}")
        self._maybe_delay(node.id)
        h = self.handles[node.id]
        return h.executor.execute(
            index, pql,
            opt=ExecOptions(
                remote=True, shards=None if shards is None else list(shards),
                cache=not nocache, delta=not nodelta,
                containers=not nocontainers, mesh=not nomesh,
                tiers=not notiers, vm=not novm,
                partial=partial, missing=set() if partial else None,
                tenant=tenant,
            ),
        )

    def send_message(self, node: Node, message: dict) -> dict:
        if node.id in self.down or node.id not in self.handles:
            raise TransportError(f"node unreachable: {node.id}")
        self._maybe_delay(node.id)
        return self.handles[node.id].receive_message(message)


class BoundTransport(Transport):
    """A LocalTransport view that stamps one node's identity on every
    outgoing call so pair partitions can be enforced.  The partition
    check runs here, then delegates to the parent's PUBLIC methods —
    tests that monkeypatch ``parent.send_message``/``query_node`` keep
    intercepting all traffic with their original signatures."""

    def __init__(self, parent: LocalTransport, src: str):
        self.parent = parent
        self.src = src

    def __getattr__(self, name):
        # everything except the two partition-checked overrides
        # delegates to the shared parent (registry, down set, bind...)
        return getattr(self.parent, name)

    def query_node(self, node: Node, index: str, pql: str, shards: list[int],
                   nocache: bool = False, nodelta: bool = False,
                   nocontainers: bool = False, nomesh: bool = False,
                   notiers: bool = False, novm: bool = False,
                   partial: bool = False,
                   tenant: str | None = None):
        self.parent._check_partition(self.src, node.id)
        extra = {}
        if nocache:
            extra["nocache"] = True
        if nodelta:
            extra["nodelta"] = True
        if nocontainers:
            extra["nocontainers"] = True
        if nomesh:
            extra["nomesh"] = True
        if notiers:
            extra["notiers"] = True
        if novm:
            extra["novm"] = True
        if partial:
            extra["partial"] = True
        if tenant is not None:
            extra["tenant"] = tenant
        if extra:
            return self.parent.query_node(node, index, pql, shards,
                                          **extra)
        # default calls keep the original 4-arg shape so tests that
        # monkeypatch parent.query_node stay compatible
        return self.parent.query_node(node, index, pql, shards)

    def send_message(self, node: Node, message: dict) -> dict:
        self.parent._check_partition(self.src, node.id)
        return self.parent.send_message(node, message)


#: circuit-breaker states (the classic closed/open/half-open machine;
#: no reference analog — Pilosa pays the full RPC timeout per query to
#: a dead-but-routable peer until SWIM marks it DOWN)
BREAKER_CLOSED = "CLOSED"
BREAKER_OPEN = "OPEN"
BREAKER_HALF_OPEN = "HALF_OPEN"


class CircuitBreaker:
    """Per-peer circuit breaker.

    CLOSED counts consecutive transport failures; at ``threshold`` it
    OPENs and ``allow()`` fast-fails every call until ``cooldown_s``
    elapses, when the next ``allow()`` transitions to HALF_OPEN and
    admits exactly ONE trial — success closes (and resets the failure
    count), failure re-opens for another cooldown.  Shed responses
    (429/503 from a live peer's admission gate) must never feed
    ``note_failure``: a shed is proof of life (see ShedByPeerError).

    Half-open trials also ride the membership heartbeat: a successful
    SWIM probe calls ``note_success`` through ``Cluster.note_probe``,
    so an idle peer's breaker heals without waiting for query traffic
    to gamble on it.

    ``clock`` is injectable for deterministic state-machine tests."""

    def __init__(self, threshold: int = 5, cooldown_s: float = 5.0,
                 clock=_time.monotonic):
        from pilosa_tpu import lockcheck as _lockcheck

        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        self.peer = ""  # stamped by Cluster.breaker for journal events
        self._lock = _lockcheck.lock("breaker")
        self._state = BREAKER_CLOSED
        self._failures = 0      # consecutive failures while CLOSED
        self._opened_t = 0.0    # clock() at the last OPEN transition
        self._probing = False   # a HALF_OPEN trial is outstanding
        self._probe_t = 0.0     # clock() when that trial was admitted
        # cumulative transition + refusal counters (breaker.* metrics)
        self.opened = 0
        self.closed = 0
        self.half_opens = 0
        self.fast_fails = 0

    def _journal(self, kind: str) -> None:
        """Journal a state transition.  Called AFTER ``self._lock`` is
        released — the journal takes its own lock and an emission site
        must never nest it under a subsystem lock."""
        from pilosa_tpu import observe as _observe

        if _observe.journal_on:
            _observe.emit(kind, peer=self.peer)

    def allow(self) -> bool:
        """True when a request may be sent to this peer.  While OPEN,
        the first call past the cooldown flips to HALF_OPEN and is
        admitted as the trial; concurrent calls during the trial keep
        fast-failing."""
        ev = None
        with self._lock:
            if self._state == BREAKER_CLOSED:
                return True
            if self._state == BREAKER_OPEN:
                if self.clock() - self._opened_t >= self.cooldown_s:
                    self._state = BREAKER_HALF_OPEN
                    self._probing = True
                    self._probe_t = self.clock()
                    self.half_opens += 1
                    ev, out = "breaker.half_open", True
                else:
                    self.fast_fails += 1
                    out = False
            # HALF_OPEN: one trial at a time — but a trial whose
            # outcome never arrived (caller crashed before noting)
            # must not wedge the breaker refusing forever: after one
            # more cooldown, admit a fresh trial
            elif (not self._probing
                    or self.clock() - self._probe_t >= self.cooldown_s):
                self._probing = True
                self._probe_t = self.clock()
                self.half_opens += 1
                ev, out = "breaker.half_open", True
            else:
                self.fast_fails += 1
                out = False
        if ev is not None:
            self._journal(ev)
        return out

    def note_success(self) -> None:
        ev = None
        with self._lock:
            if self._state != BREAKER_CLOSED:
                self.closed += 1
                ev = "breaker.close"
            self._state = BREAKER_CLOSED
            self._failures = 0
            self._probing = False
        if ev is not None:
            self._journal(ev)

    def note_failure(self) -> None:
        ev = None
        with self._lock:
            if self._state == BREAKER_HALF_OPEN:
                # the trial failed: straight back to OPEN
                self._state = BREAKER_OPEN
                self._opened_t = self.clock()
                self._probing = False
                self.opened += 1
                ev = "breaker.open"
            elif self._state != BREAKER_OPEN:
                self._failures += 1
                if self._failures >= self.threshold:
                    self._state = BREAKER_OPEN
                    self._opened_t = self.clock()
                    self.opened += 1
                    ev = "breaker.open"
        if ev is not None:
            self._journal(ev)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "consecutiveFailures": self._failures,
                "opened": self.opened,
                "closed": self.closed,
                "halfOpens": self.half_opens,
                "fastFails": self.fast_fails,
            }


class _PeerLatency:
    """EWMA mean + EWMA absolute deviation of one peer's successful
    RPC latencies — the signal hedged reads trigger on.  Touched only
    under the owning Cluster's ``_peer_lock``."""

    __slots__ = ("ewma_s", "dev_s", "n")
    ALPHA = 0.2

    def __init__(self):
        self.ewma_s = 0.0
        self.dev_s = 0.0
        self.n = 0

    def update(self, latency_s: float) -> None:
        if self.n == 0:
            self.ewma_s = latency_s
            self.dev_s = 0.0
        else:
            d = abs(latency_s - self.ewma_s)
            self.ewma_s += self.ALPHA * (latency_s - self.ewma_s)
            self.dev_s += self.ALPHA * (d - self.dev_s)
        self.n += 1


class Cluster:
    """Membership + placement + replication routing for one node's view
    of the cluster (cluster.go:186)."""

    def __init__(
        self,
        local_id: str,
        nodes: list[Node] | None = None,
        replica_n: int = 1,
        partition_n: int = DEFAULT_PARTITION_N,
        hasher=None,
        transport: Transport | None = None,
        topology_path: str | None = None,
        coordinator_id: str | None = None,
        breaker_threshold: int = 5,
        breaker_cooldown_s: float = 5.0,
    ):
        self.local_id = local_id
        self.replica_n = max(1, replica_n)
        self.partition_n = partition_n
        self.hasher = hasher or JmpHasher()
        self.transport = transport
        self.topology_path = topology_path
        self.state = STATE_STARTING
        self._lock = threading.RLock()
        self._nodes: dict[str, Node] = {}
        for n in nodes or []:
            self._nodes[n.id] = n
        if local_id not in self._nodes:
            self._nodes[local_id] = Node(id=local_id)
        self.coordinator_id = coordinator_id or sorted(self._nodes)[0]
        self._listeners: list = []
        # per-peer failure handling (the chaos round): circuit
        # breakers + latency EWMAs, both keyed by node id and guarded
        # by their own lock (never taken with self._lock held)
        from pilosa_tpu import lockcheck as _lockcheck

        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown_s = breaker_cooldown_s
        self._peer_lock = _lockcheck.lock("peers")
        self._breakers: dict[str, CircuitBreaker] = {}
        self._peer_lat: dict[str, _PeerLatency] = {}
        # per-shard routing overrides installed by the online
        # rebalance (parallel/rebalance.py): (index, shard) ->
        # (serving_ids, pending_ids).  Reads resolve to the serving
        # owners; writes go to serving + pending.  Empty outside a
        # migration window — placement stays pure ring math.
        self._route_lock = _lockcheck.lock("shard-routes")
        self._shard_routes: dict[tuple[str, int],
                                 tuple[tuple, tuple]] = {}
        if topology_path and os.path.exists(topology_path):
            self._load_topology()
        self.save_topology()

    # ------------------------------------------------------------ topology

    def _load_topology(self) -> None:
        with open(self.topology_path) as f:
            d = json.load(f)
        for nd in d.get("nodes", []):
            n = Node.from_dict(nd)
            self._nodes.setdefault(n.id, n)
        self.coordinator_id = d.get("coordinator", self.coordinator_id)

    def save_topology(self) -> None:
        """Persist member ids (the reference's .topology file,
        cluster.go:1580)."""
        if not self.topology_path:
            return
        tmp = self.topology_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "nodes": [n.to_dict() for n in self.sorted_nodes()],
                    "coordinator": self.coordinator_id,
                },
                f,
            )
        os.replace(tmp, self.topology_path)

    # ---------------------------------------------------------- membership

    def sorted_nodes(self) -> list[Node]:
        """Nodes sorted by id — the hash ring order (cluster.go:1017
        Nodes are always kept sorted)."""
        with self._lock:
            return [self._nodes[k] for k in sorted(self._nodes)]

    @property
    def local_node(self) -> Node:
        return self._nodes[self.local_id]

    def node(self, node_id: str) -> Node | None:
        return self._nodes.get(node_id)

    @property
    def is_coordinator(self) -> bool:
        return self.local_id == self.coordinator_id

    def add_node(self, node: Node) -> None:
        with self._lock:
            self._nodes[node.id] = node
            self.save_topology()

    def remove_node(self, node_id: str) -> None:
        with self._lock:
            self._nodes.pop(node_id, None)
            self.save_topology()

    def set_node_state(self, node_id: str, state: str) -> bool:
        """Returns True when a DOWN claim about THIS node was
        corrected (see apply_status's self-liveness authority) — the
        caller should broadcast the correction so stale peers heal."""
        corrected = False
        if node_id == self.local_id and state == NODE_DOWN:
            # a peer claiming WE are down is wrong by construction —
            # we are executing this call; never adopt it
            state = NODE_READY
            corrected = True
        with self._lock:
            n = self._nodes.get(node_id)
            if n is not None:
                n.state = state
            self._update_cluster_state()
        return corrected

    def set_state(self, state: str) -> None:
        with self._lock:
            self.state = state

    def set_coordinator(self, node_id: str) -> None:
        """Move the coordinator role (api.go:1193 SetCoordinator)."""
        with self._lock:
            if node_id not in self._nodes:
                raise KeyError(f"node not found: {node_id}")
            self.coordinator_id = node_id
            for n in self._nodes.values():
                n.is_coordinator = n.id == node_id
            self.save_topology()

    def _update_cluster_state(self) -> None:
        """NORMAL / DEGRADED from node healths (cluster.go:571-583):
        DEGRADED while <= replica_n - 1 nodes are down (reads can still
        be served from replicas), unavailable semantics beyond that are
        surfaced per-query by exhausted-failover errors."""
        if self.state == STATE_RESIZING:
            return
        down = sum(1 for n in self._nodes.values() if n.state == NODE_DOWN)
        if down == 0:
            self.state = STATE_NORMAL
        elif down < self.replica_n:
            self.state = STATE_DEGRADED
        else:
            self.state = STATE_DEGRADED  # still degraded; queries hitting
            # lost shards fail with exhausted-replica errors

    # ------------------------------------------------- per-peer breakers

    def breaker(self, node_id: str) -> CircuitBreaker:
        """The peer's breaker, created on first use."""
        with self._peer_lock:
            b = self._breakers.get(node_id)
            if b is None:
                b = self._breakers[node_id] = CircuitBreaker(
                    self.breaker_threshold, self.breaker_cooldown_s)
                b.peer = node_id
            return b

    def peer_allows(self, node_id: str) -> bool:
        """False when the peer's breaker refuses traffic right now
        (counts a fast-fail).  The local node always allows."""
        if node_id == self.local_id:
            return True
        return self.breaker(node_id).allow()

    def breaker_open(self, node_id: str) -> bool:
        """True when the peer's breaker is OPEN and still cooling down
        — a pure routing read (no state transition, no fast-fail
        count), used by shards_by_node to steer primaries away from
        known-bad peers exactly like DOWN markings."""
        with self._peer_lock:
            b = self._breakers.get(node_id)
        if b is None:
            return False
        with b._lock:
            return (b._state == BREAKER_OPEN
                    and b.clock() - b._opened_t < b.cooldown_s)

    def note_peer_success(self, node_id: str,
                          latency_s: float | None = None) -> None:
        """A peer answered (any HTTP answer counts — shed included).
        Feeds the breaker; a real latency sample also feeds the hedge
        EWMA (shed/probe successes pass None: their turnaround is not
        a service-time sample)."""
        self.breaker(node_id).note_success()
        if latency_s is not None:
            with self._peer_lock:
                lat = self._peer_lat.get(node_id)
                if lat is None:
                    lat = self._peer_lat[node_id] = _PeerLatency()
                lat.update(latency_s)

    def note_peer_failure(self, node_id: str) -> None:
        self.breaker(node_id).note_failure()

    def peer_latency(self, node_id: str) -> tuple[float, float, int]:
        """(ewma_s, deviation_s, n_samples) for the peer — (0,0,0)
        until the first sample."""
        with self._peer_lock:
            lat = self._peer_lat.get(node_id)
            if lat is None:
                return (0.0, 0.0, 0)
            return (lat.ewma_s, lat.dev_s, lat.n)

    def note_probe(self, node_id: str, alive: bool) -> None:
        """Membership heartbeat hand-off (parallel/membership.py): a
        successful SWIM probe is the half-open trial riding the
        heartbeat — it closes an open breaker without waiting for
        query traffic; a failed probe re-opens a half-open one.  A
        failed probe of a CLOSED breaker is left to real traffic (and
        the DOWN marking) so a single lost ping cannot open
        breakers."""
        with self._peer_lock:
            b = self._breakers.get(node_id)
        if b is None:
            return
        if alive:
            b.note_success()
        elif b.state != BREAKER_CLOSED:
            b.note_failure()

    def debug_peers(self) -> dict:
        """The /debug/peers document: per-peer breaker state, latency
        EWMA, and membership state."""
        out = {}
        for n in self.sorted_nodes():
            if n.id == self.local_id:
                continue
            with self._peer_lock:
                b = self._breakers.get(n.id)
            ewma, dev, samples = self.peer_latency(n.id)
            out[n.id] = {
                "uri": n.uri,
                "nodeState": n.state,
                "breaker": (b.snapshot() if b is not None
                            else {"state": BREAKER_CLOSED}),
                "latencyEwmaMs": round(ewma * 1e3, 3),
                "latencyDevMs": round(dev * 1e3, 3),
                "latencySamples": samples,
            }
        return out

    def publish_breaker_gauges(self, stats) -> None:
        """breaker.* gauge family for /metrics and /debug/vars.
        Cumulative transition counts publish as gauges (they are
        already totals — the devobs discipline)."""
        with self._peer_lock:
            breakers = list(self._breakers.values())
        n_open = sum(1 for b in breakers if b.state != BREAKER_CLOSED)
        stats.gauge("breaker.tracked", len(breakers))
        stats.gauge("breaker.open", n_open)
        stats.gauge("breaker.opened_total",
                    sum(b.opened for b in breakers))
        stats.gauge("breaker.closed_total",
                    sum(b.closed for b in breakers))
        stats.gauge("breaker.half_opens_total",
                    sum(b.half_opens for b in breakers))
        stats.gauge("breaker.fast_fails_total",
                    sum(b.fast_fails for b in breakers))

    # ------------------------------------------------ rebalance routing

    def set_shard_route(self, index: str, shard: int,
                        serving, pending=()) -> None:
        """Install (or replace) a per-shard routing override — the
        online rebalance's dual-write / cutover states.  ``serving``
        ids answer reads; ``serving + pending`` receive writes."""
        with self._route_lock:
            self._shard_routes[(index, int(shard))] = (
                tuple(serving), tuple(pending))

    def clear_shard_route(self, index: str, shard: int) -> None:
        with self._route_lock:
            self._shard_routes.pop((index, int(shard)), None)

    def clear_shard_routes(self) -> list[tuple[str, int]]:
        """Drop every override (rebalance commit/abort).  Returns the
        keys that were routed so callers can invalidate caches."""
        with self._route_lock:
            keys = list(self._shard_routes)
            self._shard_routes.clear()
        return keys

    def shard_route(self, index: str, shard: int
                    ) -> tuple[tuple, tuple] | None:
        """(serving_ids, pending_ids) for a mid-migration shard, or
        None when placement is pure ring math."""
        with self._route_lock:
            if not self._shard_routes:
                return None
            return self._shard_routes.get((index, int(shard)))

    def shard_routes_snapshot(self) -> dict:
        """The /debug/rebalance routing table view."""
        with self._route_lock:
            return {
                f"{index}/{shard}": {"serving": list(s),
                                     "pending": list(p)}
                for (index, shard), (s, p)
                in sorted(self._shard_routes.items())
            }

    def write_nodes(self, index: str, shard: int) -> list[Node]:
        """All nodes a write to this shard must reach: the serving
        owners plus, mid-migration, the pending (new) owners — the
        dual-write set."""
        nodes = self.shard_nodes(index, shard)
        route = self.shard_route(index, shard)
        if route is None:
            return nodes
        ids = {n.id for n in nodes}
        for nid in route[1]:
            if nid not in ids:
                n = self._nodes.get(nid)
                if n is not None:
                    nodes.append(n)
                    ids.add(nid)
        return nodes

    # ----------------------------------------------------------- placement

    def partition_nodes(self, p: int) -> list[Node]:
        """Owner nodes of a partition: primary by jump hash over the
        sorted ring, then the next replica_n-1 ring neighbors
        (cluster.go:902-924)."""
        nodes = self.sorted_nodes()
        if not nodes:
            return []
        start = self.hasher.hash(p, len(nodes))
        k = min(self.replica_n, len(nodes))
        return [nodes[(start + i) % len(nodes)] for i in range(k)]

    def shard_nodes(self, index: str, shard: int) -> list[Node]:
        """All owner replicas of a shard (cluster.go:883 shardNodes).
        A mid-migration routing override (set_shard_route) takes
        precedence over ring math: readers keep resolving to the
        still-authoritative serving owners until that shard's
        cutover."""
        route = self.shard_route(index, shard)
        if route is not None:
            serving = [self._nodes[nid] for nid in route[0]
                       if nid in self._nodes]
            if serving:
                return serving
        return self.partition_nodes(partition(index, shard, self.partition_n))

    def primary_shard_node(self, index: str, shard: int) -> Node:
        return self.shard_nodes(index, shard)[0]

    def owns_shard(self, node_id: str, index: str, shard: int) -> bool:
        """True when the node is a serving owner — or, mid-migration,
        a pending (dual-write) owner: pending owners must accept
        replica writes and keep their in-flight copy safe from the
        unowned-fragment cleaner."""
        route = self.shard_route(index, shard)
        if route is not None and (node_id in route[0]
                                  or node_id in route[1]):
            return True
        return any(n.id == node_id for n in self.shard_nodes(index, shard))

    def local_shards(self, index: str, shards) -> set[int]:
        """Subset of `shards` owned by this node (any replica slot)."""
        return {s for s in shards if self.owns_shard(self.local_id, index, s)}

    def shards_by_node(self, index: str, shards) -> dict[str, list[int]]:
        """Group shards by their primary owner, preferring the local node
        when it is any replica (the reference sends each shard to one
        owner, preferring itself; executor.go:2435 shardsByNode)."""
        out: dict[str, list[int]] = {}
        for s in sorted(shards):
            owners = self.shard_nodes(index, s)
            ids = [n.id for n in owners]
            target = self.local_id if self.local_id in ids else ids[0]
            # skip DOWN primaries and open-breaker peers up front;
            # failover handles mid-query loss (a fully-excluded shard
            # keeps its first owner so the breaker's half-open trial
            # still has a route)
            if target != self.local_id:
                for nid in ids:
                    if (self._nodes[nid].state != NODE_DOWN
                            and not self.breaker_open(nid)):
                        target = nid
                        break
            out.setdefault(target, []).append(s)
        return out

    def next_replica(self, index: str, shard: int, tried: set[str]) -> Node | None:
        """First owner of `shard` not yet tried and not DOWN — query-time
        failover target (executor.go:2492-2503)."""
        for n in self.shard_nodes(index, shard):
            if n.id not in tried and n.state != NODE_DOWN:
                return n
        return None

    # ------------------------------------------------------- key ownership

    def primary_for_translation(self) -> Node:
        """Key translation is single-writer: the coordinator holds every
        primary translate store (reference: non-primaries tail the
        primary over HTTP, holder.go:690)."""
        return self._nodes[self.coordinator_id]

    def to_status(self) -> dict:
        """ClusterStatus wire form (internal/private.proto ClusterStatus)."""
        return {
            "state": self.state,
            "coordinator": self.coordinator_id,
            "nodes": [n.to_dict() for n in self.sorted_nodes()],
        }

    def apply_status(self, status: dict) -> bool:
        """Adopt a coordinator-broadcast ClusterStatus (server.go:569
        receiveMessage ClusterStatus handling).

        Returns True when the status claimed THIS node is DOWN and the
        claim was corrected: a live node is the authority on its own
        liveness, and a snapshot can legitimately predate our restart
        (found by the round-5 process soak: a killed-and-restarted
        node adopted a stale self-DOWN, stayed DEGRADED forever, and
        nothing could rehabilitate it — peers heal their view of us
        via SWIM probes, but nobody probes us on our behalf)."""
        corrected_self = False
        with self._lock:
            self.state = status.get("state", self.state)
            self.coordinator_id = status.get("coordinator", self.coordinator_id)
            for nd in status.get("nodes", []):
                n = Node.from_dict(nd)
                existing = self._nodes.get(n.id)
                if existing is None:
                    self._nodes[n.id] = n
                else:
                    existing.state = n.state
                    existing.uri = n.uri or existing.uri
                    existing.is_coordinator = n.is_coordinator
            ids = {nd["id"] for nd in status.get("nodes", [])}
            if ids:
                for nid in list(self._nodes):
                    # never prune ourselves on a stale status that predates
                    # our join — the local node is always a member
                    if nid not in ids and nid != self.local_id:
                        del self._nodes[nid]
            me = self._nodes.get(self.local_id)
            if me is not None and me.state == NODE_DOWN:
                me.state = NODE_READY
                corrected_self = True
                self._update_cluster_state()
            for n in self._nodes.values():
                n.is_coordinator = n.id == self.coordinator_id
            self.save_topology()
        return corrected_self
