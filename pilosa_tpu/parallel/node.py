"""ClusterNode: one node's wiring of holder + cluster + executor, with
the control-plane message dispatch.

Parity target: the broadcast bus and message dispatch of the reference
(broadcast.go:30 broadcaster, server.go:569-704 receiveMessage /
SendSync): schema DDL, shard creation, and cluster status propagate to
every node; the HTTP server layer later wraps this object and exposes
the same surface over the wire.
"""

from __future__ import annotations

import threading

from pilosa_tpu.models.field import FieldOptions
from pilosa_tpu.models.index import IndexOptions
from pilosa_tpu.parallel.cluster import Cluster, Transport, TransportError
from pilosa_tpu.serve.admission import tagged

# translate tailing + cleanup verification RPC rides the internal
# admission class (serve/admission.py)
_tagged_internal = tagged("internal")


class ClusterNode:
    """A holder + executor bound to a cluster and its transport."""

    def __init__(self, holder, cluster: Cluster, worker_pool_size: int | None = None):
        import os as _os

        from pilosa_tpu.parallel.executor import Executor
        from pilosa_tpu.parallel.hints import HintStore

        self.holder = holder
        self.cluster = cluster
        self.executor = Executor(holder, worker_pool_size, cluster=cluster)
        self.executor.node = self
        self._tail_last: dict = {}  # (index, field) -> last tail time
        self._cleanup_lock = threading.Lock()
        self._cleanup_timer: threading.Timer | None = None
        self._cleanup_deadline = 0.0
        # hinted handoff (parallel/hints.py): per-peer queues of missed
        # replica writes, disk-backed under the data dir (memory-only
        # for pathless holders); drained by the server's HintReplayer
        self.hints = HintStore(
            _os.path.join(holder.path, "hints")
            if getattr(holder, "path", None) else None)
        # anti-entropy round state (parallel/syncer.py): the resumable
        # walk cursor and the last round's outcome (/debug/antientropy)
        self.ae_cursor: tuple | None = None
        self.ae_last_round: dict = {}
        # online rebalance driver (parallel/rebalance.py), attached by
        # the server on the coordinator; None for bare library use
        self.rebalance = None
        if cluster.transport is not None and hasattr(cluster.transport, "register"):
            cluster.transport.register(cluster.local_id, self)

    # ------------------------------------------------------------ broadcast

    def broadcast(self, message: dict) -> None:
        """Synchronous send to every other node (reference SendSync,
        server.go:666-704).  Unreachable nodes are skipped — anti-entropy
        reconciles them later (the reference returns an error but has no
        rollback either)."""
        from pilosa_tpu.serve.admission import current_rpc_class, rpc_class

        t = self.cluster.transport
        if t is None:
            return
        # control-plane broadcasts default to the internal class; a
        # caller that already tagged its scope (the import fan-out's
        # ingest) keeps its tag
        with rpc_class(current_rpc_class() or "internal"):
            for n in self.cluster.sorted_nodes():
                if n.id == self.cluster.local_id:
                    continue
                try:
                    t.send_message(n, message)
                except TransportError:
                    pass

    # ----------------------------------------------------- schema helpers

    def create_index(self, name: str, options: IndexOptions | None = None):
        idx = self.holder.create_index_if_not_exists(name, options)
        self.broadcast(
            {
                "type": "create-index",
                "index": name,
                "options": (options or IndexOptions()).to_dict(),
            }
        )
        return idx

    def create_field(self, index: str, name: str, options: FieldOptions | None = None):
        idx = self.holder.index(index)
        if idx is None:
            raise ValueError(f"index not found: {index}")
        f = idx.create_field_if_not_exists(name, options)
        self.broadcast(
            {
                "type": "create-field",
                "index": index,
                "field": name,
                "options": (options or FieldOptions()).to_dict(),
            }
        )
        return f

    def delete_index(self, name: str) -> None:
        self.holder.delete_index(name)
        self.broadcast({"type": "delete-index", "index": name})

    def delete_field(self, index: str, name: str) -> None:
        idx = self.holder.index(index)
        if idx is not None:
            idx.delete_field(name)
        self.broadcast({"type": "delete-field", "index": index, "field": name})

    # ------------------------------------------------------------ dispatch

    def receive_message(self, msg: dict) -> dict:
        """Apply a control-plane message from a peer (reference
        Server.receiveMessage, server.go:569-664)."""
        t = msg.get("type")
        if t == "create-index":
            self.holder.create_index_if_not_exists(
                msg["index"], IndexOptions.from_dict(msg.get("options", {}))
            )
        elif t == "create-field":
            idx = self.holder.index(msg["index"])
            if idx is not None:
                idx.create_field_if_not_exists(
                    msg["field"], FieldOptions.from_dict(msg.get("options", {}))
                )
        elif t == "delete-index":
            try:
                self.holder.delete_index(msg["index"])
            except KeyError:
                pass
        elif t == "delete-field":
            idx = self.holder.index(msg["index"])
            if idx is not None:
                try:
                    idx.delete_field(msg["field"])
                except KeyError:
                    pass
        elif t == "create-shard":
            # reference CreateShardMessage (view.go:263-305): keep every
            # node's available-shard bitmaps global so query fan-out sees
            # remote shards.
            idx = self.holder.index(msg["index"])
            if idx is not None:
                f = idx.field(msg["field"])
                if f is not None:
                    f._note_shard(int(msg["shard"]))
        elif t == "import-roaring":
            # replica delivery of a roaring import (api.import_roaring
            # origin fan-out; reference client.ImportRoaring remote=true)
            import base64 as _b64i

            from pilosa_tpu.models.view import VIEW_STANDARD

            idx = self.holder.index(msg["index"])
            f = None if idx is None else idx.field(msg["field"])
            if f is None:
                return {"ok": False, "error": "field not found"}
            shard = int(msg["shard"])
            refuse = self._refuse_unowned_import(msg["index"], shard)
            if refuse is not None:
                return refuse
            for vname, b in (msg.get("views") or {}).items():
                view = f.create_view_if_not_exists(vname or VIEW_STANDARD)
                frag = view.create_fragment_if_not_exists(shard)
                frag.import_roaring(_b64i.b64decode(b),
                                    clear=bool(msg.get("clear")))
                f._note_shard(shard)
        elif t == "import":
            idx = self.holder.index(msg["index"])
            f = None if idx is None else idx.field(msg["field"])
            if f is None:
                return {"ok": False, "error": "field not found"}
            if msg["cols"]:
                refuse = self._gate_import_cols(msg["index"], msg["cols"])
                if refuse is not None:
                    return refuse
            ts = msg.get("timestamps")
            if ts is not None:
                import datetime as _dt

                ts = [None if t_ is None else _dt.datetime.fromisoformat(t_)
                      for t_ in ts]
            f.import_bits(msg["rows"], msg["cols"], ts,
                          clear=bool(msg.get("clear")))
            if not msg.get("clear"):
                idx.import_existence(msg["cols"])
        elif t == "import-value":
            idx = self.holder.index(msg["index"])
            f = None if idx is None else idx.field(msg["field"])
            if f is None:
                return {"ok": False, "error": "field not found"}
            if msg["cols"]:
                refuse = self._gate_import_cols(msg["index"], msg["cols"])
                if refuse is not None:
                    return refuse
            f.import_values(msg["cols"], msg["values"])
            idx.import_existence(msg["cols"])
        elif t == "fragment-blocks":
            frag = self._fragment(msg, create=False)
            if frag is None:
                return {"ok": True, "blocks": []}
            blocks, hit = frag.blocks_with_flag()
            from pilosa_tpu.parallel import syncer as _syncer

            # digest-cache accounting for the SERVING side of the
            # exchange too: a quiescent AE round must re-checksum
            # nothing on either end
            _syncer.note_digest(hit)
            return {"ok": True, "blocks": blocks}
        elif t == "fragment-block-data":
            frag = self._fragment(msg, create=False)
            if frag is None:
                return {"ok": True, "rowIDs": [], "columnIDs": []}
            rows, cols = frag.block_data(int(msg["block"]))
            return {"ok": True, "rowIDs": rows, "columnIDs": cols}
        elif t == "fragment-import":
            frag = self._fragment(msg, create=True)
            if frag is None:
                return {"ok": False, "error": "field not found"}
            frag.import_positions(msg["positions"])
        elif t == "attr-blocks":
            store = self._attr_store(msg)
            blocks = [] if store is None else [
                {"id": b, "checksum": d.hex()} for b, d in store.blocks()
            ]
            return {"ok": True, "blocks": blocks}
        elif t == "attr-block-data":
            store = self._attr_store(msg)
            attrs = {} if store is None else {
                str(k): v
                for k, v in store.block_data(int(msg["block"])).items()
            }
            return {"ok": True, "attrs": attrs}
        elif t == "node-join":
            # Join handshake (the memberlist-join equivalent;
            # gossip/gossip.go:65-123, coordinator resize-on-join
            # cluster.go:1141 listenForJoins): the coordinator runs a
            # resize job moving this node's newly-owned fragments to it,
            # then broadcasts the new ClusterStatus.  A non-coordinator
            # seed forwards the join to the coordinator.
            from pilosa_tpu.parallel.cluster import Node as _Node
            from pilosa_tpu.parallel.resize import Resizer

            if not self.cluster.is_coordinator:
                return self._forward_to_coordinator(msg)
            n = _Node.from_dict(msg["node"])
            if self.cluster.node(n.id) is not None:
                # re-join of a known member (restart): refresh its uri
                # and tell everyone, or peers keep dialing the old one
                self.cluster.node(n.id).uri = n.uri or self.cluster.node(n.id).uri
                self.cluster.save_topology()
                self.broadcast({"type": "cluster-status",
                                "status": self.cluster.to_status()})
            else:
                Resizer(self).run(add=n)
            # nodeStatus lets the (re)joiner catch up on shards created
            # while it was away
            return {"ok": True, "status": self.cluster.to_status(),
                    "nodeStatus": self.node_status()}
        elif t in ("node-leave", "remove-node"):
            from pilosa_tpu.parallel.resize import Resizer

            if not self.cluster.is_coordinator:
                return self._forward_to_coordinator(
                    {"type": "remove-node", "node": msg["node"]})
            Resizer(self).run(remove_id=msg["node"])
        elif t == "node-removed":
            # This node was administratively removed: detach into a
            # standalone cluster so its background loops stop touching
            # the old members (reference: removed node receives the new
            # ClusterStatus and shuts down its participation).
            from pilosa_tpu.parallel.cluster import STATE_NORMAL

            with self.cluster._lock:
                me = self.cluster.local_node
                self.cluster._nodes = {me.id: me}
                self.cluster.coordinator_id = me.id
                me.is_coordinator = True
                self.cluster.state = STATE_NORMAL
                self.cluster.save_topology()
        elif t == "resize-instruction":
            from pilosa_tpu.parallel.resize import follow_resize_instruction

            return follow_resize_instruction(self, msg)
        elif t == "rebalance-begin":
            from pilosa_tpu.parallel import rebalance as _rebalance

            return _rebalance.apply_begin(self, msg)
        elif t == "rebalance-transfer":
            from pilosa_tpu.parallel import rebalance as _rebalance

            return _rebalance.follow_transfer(self, msg)
        elif t == "rebalance-cutover":
            from pilosa_tpu.parallel import rebalance as _rebalance

            return _rebalance.apply_cutover(self, msg)
        elif t == "rebalance-abort":
            from pilosa_tpu.parallel import rebalance as _rebalance

            return _rebalance.apply_abort(self, msg)
        elif t == "rebalance-commit":
            from pilosa_tpu.parallel import rebalance as _rebalance

            return _rebalance.apply_commit(self, msg)
        elif t == "fragment-views":
            idx = self.holder.index(msg["index"])
            f = None if idx is None else idx.field(msg["field"])
            views = []
            if f is not None:
                shard = int(msg["shard"])
                for vname, view in f.views.items():
                    if view.fragment(shard) is not None:
                        views.append(vname)
            return {"ok": True, "views": views}
        elif t == "fragment-data-b64":
            import base64 as _b64

            frag = self._fragment(msg, create=False)
            if frag is None:
                return {"ok": False, "error": "fragment not found"}
            return {"ok": True,
                    "data": _b64.b64encode(frag.to_roaring()).decode()}
        elif t == "holder-cleanup":
            self.request_cleanup()
        elif t == "ping":
            # piggybacked dissemination (SWIM, membership.py): the
            # prober's state view rides the ping; disagreements queue
            # as PROBE HINTS for our next round — never blind state
            # writes, so stale gossip cannot flap a healthy node
            states = msg.get("states") or {}
            disagree = []
            for nid, st in states.items():
                if nid == self.cluster.local_id:
                    continue
                known = self.cluster.node(nid)
                if known is not None and known.state != st:
                    disagree.append(nid)
            if disagree:
                from pilosa_tpu.parallel import membership

                membership.add_hints(self, disagree)
            return {"ok": True, "state": self.cluster.state,
                    "node_states": {n.id: n.state
                                    for n in self.cluster.sorted_nodes()}}
        elif t == "ping-req":
            # SWIM indirect probe: dial the suspect on the prober's
            # behalf (a broken prober<->suspect link must not produce
            # a false DOWN)
            from pilosa_tpu.parallel import membership

            target = self.cluster.node(msg.get("target", ""))
            # bounded relay dial: the prober gave up on its own short
            # budget; this handler thread must not sit on a 30 s
            # default timeout for a packet-swallowing dead host
            alive = (target is not None and target.id != self.cluster.local_id
                     and membership.ping(self, target, timeout=2.0))
            return {"ok": True, "alive": bool(alive)}
        elif t == "collective-time-bounds":
            # open-ended time-range resolution: report this process's
            # local view time span per field so the coordinator can
            # write the GLOBAL clamp into the collective query text
            # (parallel/spmd.py _resolve_open_time_ranges)
            from pilosa_tpu.models.timequantum import TIME_FORMAT

            idx = self.holder.index(msg["index"])
            if idx is None:
                return {"ok": False, "error": f"unknown index {msg['index']!r}"}
            out = {}
            for fname in msg["fields"]:
                f = idx.field(fname)
                times = f.time_view_times() if f is not None else []
                out[fname] = ([min(times).strftime(TIME_FORMAT),
                               max(times).strftime(TIME_FORMAT)]
                              if times else None)
            return {"ok": True, "bounds": out}
        elif t == "collective-prepare":
            # phase 1 of a coordinator-initiated collective: validate
            # and promise without entering (parallel/spmd.py)
            from pilosa_tpu.parallel import spmd

            return spmd.prepare_collective(
                self, msg["index"], msg["query"],
                row_gather_bytes=msg.get("rowGatherBytes"))
        elif t == "collective-execute":
            # join a coordinator-initiated SPMD collective query: every
            # process must enter the same program (parallel/spmd.py);
            # the replicated result is discarded here — the coordinator
            # answers the client
            from pilosa_tpu.parallel import spmd

            try:
                spmd.join_collective(
                    self, msg["index"], msg["query"],
                    row_gather_bytes=msg.get("rowGatherBytes"))
            except Exception as e:  # noqa: BLE001 — report, don't crash the bus
                return {"ok": False, "error": repr(e)}
            return {"ok": True}
        elif t == "recalculate-caches":
            self.recalculate_caches()
        elif t == "translate-keys":
            # single-writer key allocation: only the coordinator
            # (primary) creates ids (reference holder.go:690: non-primary
            # stores are read-only and tail the primary)
            if not self.cluster.is_coordinator:
                return self._forward_to_coordinator(msg)
            store = self._translate_store(msg["index"], msg.get("field"))
            if store is None:
                return {"ok": False, "error": "no translate store"}
            ids = store.translate_keys(msg["keys"], create=True)
            return {"ok": True,
                    "pairs": [{"id": i, "key": k}
                              for i, k in zip(ids, msg["keys"])]}
        elif t == "translate-entries":
            store = self._translate_store(msg["index"], msg.get("field"))
            if store is None:
                return {"ok": True, "entries": []}
            entries = store.entries(int(msg.get("after", 0)))
            return {"ok": True, "entries": [
                {"offset": o, "id": i, "key": k} for o, i, k in entries]}
        elif t == "node-status":
            self.apply_node_status(msg)
        elif t == "cluster-status":
            if self.cluster.apply_status(msg["status"]):
                # the snapshot claimed we are DOWN (stale, predating
                # our restart): we corrected our own entry; tell the
                # cluster so stale peer views heal too
                self._broadcast_self_alive()
            self.update_translate_writability()
        elif t == "node-state":
            if self.cluster.set_node_state(msg["node"], msg["state"]):
                # same healing for a direct stale claim about us —
                # the claimer's OTHER recipients adopted it verbatim
                self._broadcast_self_alive()
        else:
            return {"ok": False, "error": f"unknown message type: {t}"}
        return {"ok": True}

    def remove_node(self, node_id: str) -> None:
        """Remove a member via a coordinator-driven resize job that
        re-homes its fragments first (api.go:1226 RemoveNode).  Non-
        coordinator nodes forward to the coordinator."""
        from pilosa_tpu.parallel.resize import Resizer

        if self.cluster.is_coordinator:
            Resizer(self).run(remove_id=node_id)
            return
        coord = self.cluster.node(self.cluster.coordinator_id)
        if coord is None or self.cluster.transport is None:
            raise RuntimeError("no coordinator reachable for remove-node")
        resp = self.cluster.transport.send_message(
            coord, {"type": "remove-node", "node": node_id})
        if not resp.get("ok", True):
            raise RuntimeError(resp.get("error", "remove-node failed"))

    def _broadcast_self_alive(self) -> None:
        """Push a node-state READY for ourselves after overruling a
        stale self-DOWN claim (apply_status/set_node_state self-
        liveness authority): peers that adopted the stale claim heal
        immediately instead of waiting for their next SWIM sample of
        us.  Receivers' set_node_state never re-broadcasts a READY,
        so this cannot loop."""
        from pilosa_tpu.parallel.cluster import NODE_READY

        self.broadcast({"type": "node-state",
                        "node": self.cluster.local_id,
                        "state": NODE_READY})

    def _gate_import_cols(self, index: str, cols) -> dict | None:
        """Ownership gate for import/import-value deliveries.  The
        origin fan-out groups bits by shard before sending, so a
        well-formed delivery is single-shard — but the gate used to
        check only ``cols[0]``'s shard, which would let a malformed (or
        stale-client) multi-shard payload slip bits for OTHER shards
        past the ownership check.  Validate every column lands in the
        first column's shard before consulting ownership at all."""
        import numpy as np

        from pilosa_tpu.shardwidth import SHARD_WIDTH

        shards = np.unique(
            np.asarray(cols, dtype=np.int64) // SHARD_WIDTH)
        if len(shards) != 1:
            return {"ok": False,
                    "error": f"import delivery spans shards "
                             f"{[int(s) for s in shards[:8]]}; replica "
                             f"deliveries must be single-shard"}
        return self._refuse_unowned_import(index, int(shards[0]))

    def _refuse_unowned_import(self, index: str,
                               shard: int) -> dict | None:
        """Reference api.go ErrClusterDoesNotOwnShard: a replica
        delivery for a shard this node does not own (per its CURRENT
        view) is refused, not silently absorbed — a stale-view origin
        would otherwise land bits on an ex-owner whose fragments the
        post-resize sweep deletes, losing the write.  The origin
        re-resolves owners and retries (api._send_to_owners)."""
        if self.cluster.transport is None \
                or len(self.cluster.sorted_nodes()) < 2:
            return None
        if self.cluster.owns_shard(self.cluster.local_id, index, shard):
            return None
        from pilosa_tpu.parallel.cluster import UNOWNED_MARKER

        return {"ok": False, "unowned": True,
                "error": f"{UNOWNED_MARKER}: node does not own shard "
                         f"{shard}"}

    def cleanup_unowned(self) -> None:
        """Delete local fragments for shards this node no longer owns
        (reference holderCleaner, holder.go:1103-1154).  Shard
        availability bookkeeping is left global — other nodes still
        hold the shard.

        RESCUE-BEFORE-DELETE (round 5): a fragment is deleted only
        after a current owner PROVABLY holds a superset of its bits
        (block-checksum verified, diffs pushed via the AE fragment
        syncer first).  Bits can legitimately strand on an ex-owner —
        a write whose origin's own stale view listed this node as
        owner has no peer that could refuse it — and deleting such a
        fragment would lose the only copy.  Unverifiable fragments
        (owners unreachable) are kept for the next sweep."""
        if self.cluster.transport is None or len(self.cluster.sorted_nodes()) < 2:
            return
        for d in self.holder.schema():
            iname = d["name"]
            idx = self.holder.index(iname)
            if idx is None:
                continue
            for f in idx.all_fields():
                for vname, view in list(f.views.items()):
                    for shard in list(view.fragments):
                        if self.cluster.owns_shard(
                                self.cluster.local_id, iname, shard):
                            continue
                        if self._owner_covers_fragment(
                                iname, f.name, vname, shard):
                            view.delete_fragment(shard)

    def _owner_covers_fragment(self, index: str, field: str,
                               vname: str, shard: int) -> bool:
        """True when some current owner verifiably holds every bit of
        the local (unowned) fragment: run one AE reconcile pass (which
        pushes any bits the owners are missing), then require a
        block-checksum match from at least one owner.  AE replicates
        among owners afterward, so one verified copy suffices."""
        from pilosa_tpu.parallel.syncer import FragmentSyncer

        frag = self.local_fragment(index, field, vname, shard,
                                   create=False)
        if frag is None:
            return True
        local = {b["id"]: b["checksum"] for b in frag.blocks()}
        if not local:
            return True  # empty fragment: nothing to lose
        # verify-first: after a clean resize transfer the owners hold
        # identical fragments, so the common case costs ONE checksum
        # RPC per owner and no sync pass
        if self._any_owner_matches(index, field, vname, shard, local):
            return True
        try:
            FragmentSyncer(self, index, field, vname, shard).sync()
        except Exception:  # noqa: BLE001 — keep the data on any doubt
            return False
        # sync may have pulled peer bits INTO this fragment too;
        # re-read the local checksums before re-verifying
        local = {b["id"]: b["checksum"] for b in frag.blocks()}
        return self._any_owner_matches(index, field, vname, shard,
                                       local)

    @_tagged_internal
    def _any_owner_matches(self, index: str, field: str, vname: str,
                           shard: int, local: dict) -> bool:
        from pilosa_tpu.parallel.cluster import TransportError

        for n in self.cluster.shard_nodes(index, shard):
            if n.id == self.cluster.local_id:
                continue
            try:
                resp = self.cluster.transport.send_message(n, {
                    "type": "fragment-blocks", "index": index,
                    "field": field, "view": vname, "shard": shard,
                })
            except TransportError:
                continue
            peer = {b["id"]: b["checksum"]
                    for b in resp.get("blocks", [])}
            if all(peer.get(bid) == cs for bid, cs in local.items()):
                return True
        return False

    def request_cleanup(self) -> None:
        """Schedule cleanup_unowned at least one grace period after
        the LATEST request, coalescing into one pending timer.

        Deleting re-homed fragments IMMEDIATELY at resize commit loses
        reads (found by the round-5 process soak, data bit-exact on
        disk): a query planned under the pre-commit topology can
        execute its remote sub-queries AFTER the old owner's cleanup,
        and an absent fragment legitimately reads as zero bits — a
        silent undercount, not an error.  The reference never has this
        race window small: its holderCleaner runs on a slow periodic
        cadence (holder.go:1103), so old owners keep their fragments
        long past any in-flight query.  The grace period restores that
        property while keeping disk bounded.

        Every request EXTENDS the pending sweep's deadline (a fixed
        timer would give a resize that commits just before an earlier
        sweep fires near-zero effective grace — the same race back),
        and the timer slot is cleared BEFORE the sweep runs, so a
        request arriving mid-sweep schedules a fresh timer instead of
        being lost.  PILOSA_TPU_CLEANUP_GRACE_S=0 restores immediate
        cleanup."""
        import os

        grace = float(os.environ.get("PILOSA_TPU_CLEANUP_GRACE_S",
                                     "30.0"))
        if grace <= 0:
            self.cleanup_unowned()
            return
        import time as _time

        with self._cleanup_lock:
            self._cleanup_deadline = _time.monotonic() + grace
            if self._cleanup_timer is None:
                self._schedule_cleanup_locked(grace)

    def _schedule_cleanup_locked(self, delay: float) -> None:
        t = threading.Timer(delay, self._cleanup_fire)
        t.daemon = True
        self._cleanup_timer = t
        t.start()

    def _cleanup_fire(self) -> None:
        import time as _time

        with self._cleanup_lock:
            remaining = self._cleanup_deadline - _time.monotonic()
            if remaining > 0.05:
                # deadline was extended by a later request — honor it
                self._schedule_cleanup_locked(remaining)
                return
            self._cleanup_timer = None
        try:
            self.cleanup_unowned()
        except Exception as e:  # noqa: BLE001 — a timer thread must
            # not die silently NOR crash the process; shutdown races
            # land here too, but persistent failures stay visible
            msg = (f"deferred holder-cleanup failed: "
                   f"{type(e).__name__}: {e}")
            try:
                log = getattr(self.executor, "logger", None)
                if log is not None:
                    log.printf("%s", msg)
                else:
                    import sys

                    print(msg, file=sys.stderr)
            except Exception:  # noqa: BLE001
                pass

    def resize_abort(self) -> None:
        """Abort an in-flight resize job (api.go:1250 ResizeAbort);
        overridden by the resize subsystem when attached.  An active
        ONLINE rebalance aborts through its driver instead — routing
        reverts to the old topology without gating anything."""
        driver = getattr(self, "rebalance", None)
        if driver is not None and driver.active():
            driver.abort()
            return
        from pilosa_tpu.parallel.cluster import STATE_NORMAL

        self.cluster.set_state(STATE_NORMAL)
        self.broadcast({"type": "cluster-status", "status": self.cluster.to_status()})

    def _translate_store(self, index: str, field: str | None):
        idx = self.holder.index(index)
        if idx is None:
            return None
        if field:
            f = idx.field(field)
            return None if f is None else f.translate_store
        return idx.translate_store

    def recalculate_caches(self) -> None:
        """Recompute every fragment's TopN cache on this node
        (reference holder.RecalculateCaches; broadcast by the API so
        all nodes refresh, api.go:1139).  Dicts are snapshotted —
        concurrent schema/import requests mutate them.  BSI plane views
        have no TopN semantics and are skipped."""
        from pilosa_tpu.models.view import VIEW_BSI_PREFIX

        for idx in list(self.holder.indexes.values()):
            for f in list(idx.fields.values()):
                for vname, view in list(f.views.items()):
                    if vname.startswith(VIEW_BSI_PREFIX):
                        continue
                    for frag in list(view.fragments.values()):
                        frag.recalculate_cache()

    def translate_keys_cluster(self, index: str, field: str | None, keys,
                               create: bool = False):
        """Key -> id with single-writer semantics: existing keys resolve
        locally; creation routes to the coordinator and the returned
        (id, key) pairs are applied to the local replica immediately
        (reference executor translate + primary store, holder.go:690,
        executor.go:2610).  This is the ONLY allocation entry point —
        executor and API both delegate here."""
        from pilosa_tpu.parallel.cluster import STATE_STARTING

        store = self._translate_store(index, field)
        if store is None:
            raise ValueError(f"no translate store for {index}/{field}")
        ids = store.translate_keys(list(keys), create=False)
        missing = [k for k, i in zip(keys, ids) if i is None]
        if not missing:
            return ids
        if not create:
            # read-through: the primary may have allocated keys this
            # replica hasn't tailed yet — catching up NOW keeps keyed
            # reads exact on every node, not just after the next
            # anti-entropy sweep (the reference's replicas tail the
            # primary's entry stream continuously, holder.go:690-878)
            if (self.cluster.transport is not None
                    and len(self.cluster.sorted_nodes()) > 1
                    and not self.cluster.is_coordinator
                    and self._tail_throttled(index, field, store)):
                return store.translate_keys(list(keys), create=False)
            return ids
        if (self.cluster.transport is not None
                and self.cluster.state == STATE_STARTING):
            # membership not yet known: allocating locally here could
            # collide with ids the coordinator hands out (split-brain);
            # the API rejects queries in STARTING for the same reason
            raise RuntimeError(
                "cannot allocate keys before the cluster is joined")
        clustered = (self.cluster.transport is not None
                     and len(self.cluster.sorted_nodes()) > 1)
        if not clustered or self.cluster.is_coordinator:
            return store.translate_keys(list(keys), create=True)
        from pilosa_tpu.serve.admission import current_rpc_class, rpc_class

        # key ALLOCATION serves writes: ride the caller's class when
        # tagged (import fan-out = ingest), default ingest — never
        # internal, which yields under query pressure and would make
        # an already-admitted keyed query fail precisely because the
        # coordinator is busy with queries (priority inversion)
        with rpc_class(current_rpc_class() or "ingest"):
            resp = self._forward_to_coordinator({
                "type": "translate-keys", "index": index, "field": field,
                "keys": missing,
            })
        if not resp.get("ok"):
            raise RuntimeError(
                f"coordinator key allocation failed: {resp.get('error')}")
        by_key = {p["key"]: p["id"] for p in resp["pairs"]}
        # backfill the local replica in entry order (never out-of-band —
        # offsets must stay gapless so tailing resumes correctly)
        self._tail_store(index, field, store)
        return [i if i is not None else by_key.get(k)
                for k, i in zip(keys, ids)]

    def translate_ids_cluster(self, index: str, field: str | None, ids):
        """Id -> key with the same read-through as key lookups: a miss
        on a non-coordinator replica tails the primary's entry stream
        once and retries, so result translation is exact on every node
        immediately after a write (reference holder.go:690-878)."""
        store = self._translate_store(index, field)
        if store is None:
            return [None] * len(list(ids))
        ids = list(ids)
        keys = store.translate_ids(ids)
        if (any(k is None for k in keys)
                and self.cluster.transport is not None
                and len(self.cluster.sorted_nodes()) > 1
                and not self.cluster.is_coordinator
                and self._tail_throttled(index, field, store)):
            keys = store.translate_ids(ids)
        return keys

    def set_coordinator(self, node_id: str) -> None:
        """Move the coordinator role, refresh translate writability, and
        tell everyone (api.go:1193 SetCoordinator — the reference
        broadcasts SetCoordinatorMessage)."""
        self.cluster.set_coordinator(node_id)
        self.update_translate_writability()
        self.broadcast({"type": "cluster-status",
                        "status": self.cluster.to_status()})

    def update_translate_writability(self) -> None:
        """Mark keyed stores read-only on non-coordinator members —
        defense-in-depth under the RPC routing (reference: non-primary
        stores ARE read-only, translate.go:35, holder.go:690).
        apply_entry bypasses the flag, so tailing still works."""
        clustered = (self.cluster.transport is not None
                     and len(self.cluster.sorted_nodes()) > 1)
        ro = clustered and not self.cluster.is_coordinator
        for d in self.holder.schema():
            idx = self.holder.index(d["name"])
            if idx is None:
                continue
            if idx.options.keys:
                idx.translate_store.set_read_only(ro)
            for f in idx.public_fields():
                if f.options.keys:
                    f.translate_store.set_read_only(ro)

    #: minimum seconds between read-through tail RPCs per store; bounds
    #: the coordinator round-trip rate when clients probe keys that
    #: never resolve, at the cost of a (tiny) staleness window for
    #: brand-new keys — still far fresher than the reference's
    #: background tail loop
    TAIL_THROTTLE_S = 0.1

    def _tail_throttled(self, index: str, field: str | None, store) -> int:
        import time

        key = (index, field)
        now = time.monotonic()
        last = self._tail_last.get(key, 0.0)
        if now - last < self.TAIL_THROTTLE_S:
            return 0
        self._tail_last[key] = now
        applied = self._tail_store(index, field, store)
        if applied:
            # progress was made; allow an immediate follow-up
            self._tail_last.pop(key, None)
        return applied

    @_tagged_internal
    def _tail_store(self, index: str, field: str | None, store) -> int:
        # translate replication (tailing the primary's entry stream)
        # is internal-class traffic: it may yield under query pressure
        # and catch up on the next tail, never starving user queries
        coord = self.cluster.node(self.cluster.coordinator_id)
        if coord is None:
            return 0
        applied = 0
        while True:
            before = store.max_offset()
            try:
                resp = self.cluster.transport.send_message(coord, {
                    "type": "translate-entries", "index": index,
                    "field": field, "after": before,
                })
            except TransportError:
                return applied
            entries = resp.get("entries", [])
            if not entries:
                return applied
            store.apply_entries(
                [(e["offset"], e["id"], e["key"]) for e in entries])
            applied += len(entries)
            if store.max_offset() <= before:
                # no forward progress (conflicting local entries were
                # ignored by apply): bail rather than spin forever
                return applied

    def tail_translate_entries(self) -> int:
        """Pull new key-translation entries from the coordinator for all
        keyed indexes/fields (the reference's TranslateEntryReader tail
        loop, holder.go:690-878).  Returns entries applied."""
        if (self.cluster.transport is None or self.cluster.is_coordinator
                or len(self.cluster.sorted_nodes()) < 2):
            return 0
        coord = self.cluster.node(self.cluster.coordinator_id)
        if coord is None:
            return 0
        applied = 0
        targets = []
        for d in self.holder.schema():
            idx = self.holder.index(d["name"])
            if idx is None:
                continue
            if idx.options.keys:
                targets.append((d["name"], None, idx.translate_store))
            for f in idx.public_fields():
                if f.options.keys:
                    targets.append((d["name"], f.name, f.translate_store))
        for index, field, store in targets:
            applied += self._tail_store(index, field, store)
        return applied

    def _forward_to_coordinator(self, msg: dict) -> dict:
        coord = self.cluster.node(self.cluster.coordinator_id)
        if coord is None or self.cluster.transport is None:
            return {"ok": False, "error": "no coordinator reachable"}
        try:
            return self.cluster.transport.send_message(coord, msg)
        except TransportError as e:
            return {"ok": False, "error": str(e)}

    def local_fragment(self, index: str, field: str, view: str, shard: int,
                       create: bool = False):
        """Resolve (index, field, view, shard) -> Fragment; the single
        resolution path shared by message dispatch and the syncer."""
        idx = self.holder.index(index)
        f = None if idx is None else idx.field(field)
        if f is None:
            return None
        v = f.view(view)
        if v is None:
            if not create:
                return None
            v = f.create_view_if_not_exists(view)
        frag = v.fragment(shard)
        if frag is None and create:
            frag = v.create_fragment_if_not_exists(shard)
            f._note_shard(shard)
        return frag

    def attr_store(self, index: str, field: str | None):
        idx = self.holder.index(index)
        if idx is None:
            return None
        if not field:
            return idx.column_attrs
        f = idx.field(field)
        return None if f is None else f.row_attrs

    def _fragment(self, msg: dict, create: bool):
        return self.local_fragment(msg["index"], msg["field"], msg["view"],
                                   int(msg["shard"]), create)

    def _attr_store(self, msg: dict):
        return self.attr_store(msg["index"], msg.get("field"))

    def node_status(self) -> dict:
        """Per-field available shards (reference NodeStatus,
        internal/private.proto; merged remotely via
        Field.AddRemoteAvailableShards, field.go:263-360)."""
        indexes: dict[str, dict[str, list[int]]] = {}
        for d in self.holder.schema():
            idx = self.holder.index(d["name"])
            if idx is None:
                continue
            fields = {}
            for f in idx.all_fields():
                shards = sorted(f.available_shards())
                if shards:
                    fields[f.name] = shards
            if fields:
                indexes[d["name"]] = fields
        return {"type": "node-status", "node": self.cluster.local_id,
                "indexes": indexes}

    def broadcast_node_status(self) -> None:
        self.broadcast(self.node_status())

    def apply_node_status(self, msg: dict) -> None:
        for iname, fields in msg.get("indexes", {}).items():
            idx = self.holder.index(iname)
            if idx is None:
                continue
            for fname, shards in fields.items():
                f = idx.field(fname)
                if f is not None:
                    f.add_remote_available_shards(set(shards))

    def note_shard_created(self, index: str, field: str, shard: int) -> None:
        """Broadcast new-shard existence after a local write created it."""
        self.broadcast(
            {"type": "create-shard", "index": index, "field": field, "shard": shard}
        )
