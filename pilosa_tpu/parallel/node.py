"""ClusterNode: one node's wiring of holder + cluster + executor, with
the control-plane message dispatch.

Parity target: the broadcast bus and message dispatch of the reference
(broadcast.go:30 broadcaster, server.go:569-704 receiveMessage /
SendSync): schema DDL, shard creation, and cluster status propagate to
every node; the HTTP server layer later wraps this object and exposes
the same surface over the wire.
"""

from __future__ import annotations

from pilosa_tpu.models.field import FieldOptions
from pilosa_tpu.models.index import IndexOptions
from pilosa_tpu.parallel.cluster import Cluster, Transport, TransportError


class ClusterNode:
    """A holder + executor bound to a cluster and its transport."""

    def __init__(self, holder, cluster: Cluster, worker_pool_size: int | None = None):
        from pilosa_tpu.parallel.executor import Executor

        self.holder = holder
        self.cluster = cluster
        self.executor = Executor(holder, worker_pool_size, cluster=cluster)
        self.executor.node = self
        if cluster.transport is not None and hasattr(cluster.transport, "register"):
            cluster.transport.register(cluster.local_id, self)

    # ------------------------------------------------------------ broadcast

    def broadcast(self, message: dict) -> None:
        """Synchronous send to every other node (reference SendSync,
        server.go:666-704).  Unreachable nodes are skipped — anti-entropy
        reconciles them later (the reference returns an error but has no
        rollback either)."""
        t = self.cluster.transport
        if t is None:
            return
        for n in self.cluster.sorted_nodes():
            if n.id == self.cluster.local_id:
                continue
            try:
                t.send_message(n, message)
            except TransportError:
                pass

    # ----------------------------------------------------- schema helpers

    def create_index(self, name: str, options: IndexOptions | None = None):
        idx = self.holder.create_index_if_not_exists(name, options)
        self.broadcast(
            {
                "type": "create-index",
                "index": name,
                "options": (options or IndexOptions()).to_dict(),
            }
        )
        return idx

    def create_field(self, index: str, name: str, options: FieldOptions | None = None):
        idx = self.holder.index(index)
        if idx is None:
            raise ValueError(f"index not found: {index}")
        f = idx.create_field_if_not_exists(name, options)
        self.broadcast(
            {
                "type": "create-field",
                "index": index,
                "field": name,
                "options": (options or FieldOptions()).to_dict(),
            }
        )
        return f

    def delete_index(self, name: str) -> None:
        self.holder.delete_index(name)
        self.broadcast({"type": "delete-index", "index": name})

    def delete_field(self, index: str, name: str) -> None:
        idx = self.holder.index(index)
        if idx is not None:
            idx.delete_field(name)
        self.broadcast({"type": "delete-field", "index": index, "field": name})

    # ------------------------------------------------------------ dispatch

    def receive_message(self, msg: dict) -> dict:
        """Apply a control-plane message from a peer (reference
        Server.receiveMessage, server.go:569-664)."""
        t = msg.get("type")
        if t == "create-index":
            self.holder.create_index_if_not_exists(
                msg["index"], IndexOptions.from_dict(msg.get("options", {}))
            )
        elif t == "create-field":
            idx = self.holder.index(msg["index"])
            if idx is not None:
                idx.create_field_if_not_exists(
                    msg["field"], FieldOptions.from_dict(msg.get("options", {}))
                )
        elif t == "delete-index":
            try:
                self.holder.delete_index(msg["index"])
            except KeyError:
                pass
        elif t == "delete-field":
            idx = self.holder.index(msg["index"])
            if idx is not None:
                try:
                    idx.delete_field(msg["field"])
                except KeyError:
                    pass
        elif t == "create-shard":
            # reference CreateShardMessage (view.go:263-305): keep every
            # node's available-shard bitmaps global so query fan-out sees
            # remote shards.
            idx = self.holder.index(msg["index"])
            if idx is not None:
                f = idx.field(msg["field"])
                if f is not None:
                    f._note_shard(int(msg["shard"]))
        elif t == "import":
            idx = self.holder.index(msg["index"])
            f = None if idx is None else idx.field(msg["field"])
            if f is None:
                return {"ok": False, "error": "field not found"}
            ts = msg.get("timestamps")
            if ts is not None:
                import datetime as _dt

                ts = [None if t_ is None else _dt.datetime.fromisoformat(t_)
                      for t_ in ts]
            f.import_bits(msg["rows"], msg["cols"], ts,
                          clear=bool(msg.get("clear")))
        elif t == "import-value":
            idx = self.holder.index(msg["index"])
            f = None if idx is None else idx.field(msg["field"])
            if f is None:
                return {"ok": False, "error": "field not found"}
            f.import_values(msg["cols"], msg["values"])
        elif t == "fragment-blocks":
            frag = self._fragment(msg, create=False)
            return {"ok": True,
                    "blocks": [] if frag is None else frag.blocks()}
        elif t == "fragment-block-data":
            frag = self._fragment(msg, create=False)
            if frag is None:
                return {"ok": True, "rowIDs": [], "columnIDs": []}
            rows, cols = frag.block_data(int(msg["block"]))
            return {"ok": True, "rowIDs": rows, "columnIDs": cols}
        elif t == "fragment-import":
            frag = self._fragment(msg, create=True)
            if frag is None:
                return {"ok": False, "error": "field not found"}
            frag.import_positions(msg["positions"])
        elif t == "attr-blocks":
            store = self._attr_store(msg)
            blocks = [] if store is None else [
                {"id": b, "checksum": d.hex()} for b, d in store.blocks()
            ]
            return {"ok": True, "blocks": blocks}
        elif t == "attr-block-data":
            store = self._attr_store(msg)
            attrs = {} if store is None else {
                str(k): v
                for k, v in store.block_data(int(msg["block"])).items()
            }
            return {"ok": True, "attrs": attrs}
        elif t == "node-join":
            # Join handshake (the memberlist-join equivalent;
            # gossip/gossip.go:65-123): the coordinator admits the node
            # and broadcasts the new ClusterStatus to everyone.
            from pilosa_tpu.parallel.cluster import Node as _Node

            n = _Node.from_dict(msg["node"])
            self.cluster.add_node(n)
            status = self.cluster.to_status()
            self.broadcast({"type": "cluster-status", "status": status})
            return {"ok": True, "status": status}
        elif t == "node-leave":
            self.cluster.remove_node(msg["node"])
            self.broadcast({"type": "cluster-status",
                            "status": self.cluster.to_status()})
        elif t == "node-status":
            self.apply_node_status(msg)
        elif t == "cluster-status":
            self.cluster.apply_status(msg["status"])
        elif t == "node-state":
            self.cluster.set_node_state(msg["node"], msg["state"])
        else:
            return {"ok": False, "error": f"unknown message type: {t}"}
        return {"ok": True}

    def remove_node(self, node_id: str) -> None:
        """Remove a member and broadcast the new status (api.go:1226
        RemoveNode).  When the resize subsystem is attached it drives a
        removal resize job first."""
        self.cluster.remove_node(node_id)
        self.cluster.set_coordinator(self.cluster.coordinator_id
                                     if self.cluster.node(self.cluster.coordinator_id)
                                     else sorted(n.id for n in self.cluster.sorted_nodes())[0])
        self.broadcast({"type": "cluster-status", "status": self.cluster.to_status()})

    def resize_abort(self) -> None:
        """Abort an in-flight resize job (api.go:1250 ResizeAbort);
        overridden by the resize subsystem when attached."""
        from pilosa_tpu.parallel.cluster import STATE_NORMAL

        self.cluster.set_state(STATE_NORMAL)
        self.broadcast({"type": "cluster-status", "status": self.cluster.to_status()})

    def _fragment(self, msg: dict, create: bool):
        idx = self.holder.index(msg["index"])
        f = None if idx is None else idx.field(msg["field"])
        if f is None:
            return None
        vname = msg["view"]
        view = f.view(vname)
        if view is None:
            if not create:
                return None
            view = f.create_view_if_not_exists(vname)
        frag = view.fragment(int(msg["shard"]))
        if frag is None and create:
            frag = view.create_fragment_if_not_exists(int(msg["shard"]))
            f._note_shard(int(msg["shard"]))
        return frag

    def _attr_store(self, msg: dict):
        idx = self.holder.index(msg["index"])
        if idx is None:
            return None
        if not msg.get("field"):
            return idx.column_attrs
        f = idx.field(msg["field"])
        return None if f is None else f.row_attrs

    def node_status(self) -> dict:
        """Per-field available shards (reference NodeStatus,
        internal/private.proto; merged remotely via
        Field.AddRemoteAvailableShards, field.go:263-360)."""
        indexes: dict[str, dict[str, list[int]]] = {}
        for d in self.holder.schema():
            idx = self.holder.index(d["name"])
            if idx is None:
                continue
            fields = {}
            for f in idx.public_fields():
                shards = sorted(f.available_shards())
                if shards:
                    fields[f.name] = shards
            if fields:
                indexes[d["name"]] = fields
        return {"type": "node-status", "node": self.cluster.local_id,
                "indexes": indexes}

    def broadcast_node_status(self) -> None:
        self.broadcast(self.node_status())

    def apply_node_status(self, msg: dict) -> None:
        for iname, fields in msg.get("indexes", {}).items():
            idx = self.holder.index(iname)
            if idx is None:
                continue
            for fname, shards in fields.items():
                f = idx.field(fname)
                if f is not None:
                    f.add_remote_available_shards(set(shards))

    def note_shard_created(self, index: str, field: str, shard: int) -> None:
        """Broadcast new-shard existence after a local write created it."""
        self.broadcast(
            {"type": "create-shard", "index": index, "field": field, "shard": shard}
        )
