"""ClusterNode: one node's wiring of holder + cluster + executor, with
the control-plane message dispatch.

Parity target: the broadcast bus and message dispatch of the reference
(broadcast.go:30 broadcaster, server.go:569-704 receiveMessage /
SendSync): schema DDL, shard creation, and cluster status propagate to
every node; the HTTP server layer later wraps this object and exposes
the same surface over the wire.
"""

from __future__ import annotations

from pilosa_tpu.models.field import FieldOptions
from pilosa_tpu.models.index import IndexOptions
from pilosa_tpu.parallel.cluster import Cluster, Transport, TransportError


class ClusterNode:
    """A holder + executor bound to a cluster and its transport."""

    def __init__(self, holder, cluster: Cluster, worker_pool_size: int | None = None):
        from pilosa_tpu.parallel.executor import Executor

        self.holder = holder
        self.cluster = cluster
        self.executor = Executor(holder, worker_pool_size, cluster=cluster)
        self.executor.node = self
        if cluster.transport is not None and hasattr(cluster.transport, "register"):
            cluster.transport.register(cluster.local_id, self)

    # ------------------------------------------------------------ broadcast

    def broadcast(self, message: dict) -> None:
        """Synchronous send to every other node (reference SendSync,
        server.go:666-704).  Unreachable nodes are skipped — anti-entropy
        reconciles them later (the reference returns an error but has no
        rollback either)."""
        t = self.cluster.transport
        if t is None:
            return
        for n in self.cluster.sorted_nodes():
            if n.id == self.cluster.local_id:
                continue
            try:
                t.send_message(n, message)
            except TransportError:
                pass

    # ----------------------------------------------------- schema helpers

    def create_index(self, name: str, options: IndexOptions | None = None):
        idx = self.holder.create_index_if_not_exists(name, options)
        self.broadcast(
            {
                "type": "create-index",
                "index": name,
                "options": (options or IndexOptions()).to_dict(),
            }
        )
        return idx

    def create_field(self, index: str, name: str, options: FieldOptions | None = None):
        idx = self.holder.index(index)
        if idx is None:
            raise ValueError(f"index not found: {index}")
        f = idx.create_field_if_not_exists(name, options)
        self.broadcast(
            {
                "type": "create-field",
                "index": index,
                "field": name,
                "options": (options or FieldOptions()).to_dict(),
            }
        )
        return f

    def delete_index(self, name: str) -> None:
        self.holder.delete_index(name)
        self.broadcast({"type": "delete-index", "index": name})

    def delete_field(self, index: str, name: str) -> None:
        idx = self.holder.index(index)
        if idx is not None:
            idx.delete_field(name)
        self.broadcast({"type": "delete-field", "index": index, "field": name})

    # ------------------------------------------------------------ dispatch

    def receive_message(self, msg: dict) -> dict:
        """Apply a control-plane message from a peer (reference
        Server.receiveMessage, server.go:569-664)."""
        t = msg.get("type")
        if t == "create-index":
            self.holder.create_index_if_not_exists(
                msg["index"], IndexOptions.from_dict(msg.get("options", {}))
            )
        elif t == "create-field":
            idx = self.holder.index(msg["index"])
            if idx is not None:
                idx.create_field_if_not_exists(
                    msg["field"], FieldOptions.from_dict(msg.get("options", {}))
                )
        elif t == "delete-index":
            try:
                self.holder.delete_index(msg["index"])
            except KeyError:
                pass
        elif t == "delete-field":
            idx = self.holder.index(msg["index"])
            if idx is not None:
                try:
                    idx.delete_field(msg["field"])
                except KeyError:
                    pass
        elif t == "create-shard":
            # reference CreateShardMessage (view.go:263-305): keep every
            # node's available-shard bitmaps global so query fan-out sees
            # remote shards.
            idx = self.holder.index(msg["index"])
            if idx is not None:
                f = idx.field(msg["field"])
                if f is not None:
                    f._note_shard(int(msg["shard"]))
        elif t == "import":
            idx = self.holder.index(msg["index"])
            f = None if idx is None else idx.field(msg["field"])
            if f is None:
                return {"ok": False, "error": "field not found"}
            ts = msg.get("timestamps")
            if ts is not None:
                import datetime as _dt

                ts = [None if t_ is None else _dt.datetime.fromisoformat(t_)
                      for t_ in ts]
            f.import_bits(msg["rows"], msg["cols"], ts,
                          clear=bool(msg.get("clear")))
        elif t == "import-value":
            idx = self.holder.index(msg["index"])
            f = None if idx is None else idx.field(msg["field"])
            if f is None:
                return {"ok": False, "error": "field not found"}
            f.import_values(msg["cols"], msg["values"])
        elif t == "node-join":
            # Join handshake (the memberlist-join equivalent;
            # gossip/gossip.go:65-123): the coordinator admits the node
            # and broadcasts the new ClusterStatus to everyone.
            from pilosa_tpu.parallel.cluster import Node as _Node

            n = _Node.from_dict(msg["node"])
            self.cluster.add_node(n)
            status = self.cluster.to_status()
            self.broadcast({"type": "cluster-status", "status": status})
            return {"ok": True, "status": status}
        elif t == "node-leave":
            self.cluster.remove_node(msg["node"])
            self.broadcast({"type": "cluster-status",
                            "status": self.cluster.to_status()})
        elif t == "cluster-status":
            self.cluster.apply_status(msg["status"])
        elif t == "node-state":
            self.cluster.set_node_state(msg["node"], msg["state"])
        else:
            return {"ok": False, "error": f"unknown message type: {t}"}
        return {"ok": True}

    def remove_node(self, node_id: str) -> None:
        """Remove a member and broadcast the new status (api.go:1226
        RemoveNode).  When the resize subsystem is attached it drives a
        removal resize job first."""
        self.cluster.remove_node(node_id)
        self.cluster.set_coordinator(self.cluster.coordinator_id
                                     if self.cluster.node(self.cluster.coordinator_id)
                                     else sorted(n.id for n in self.cluster.sorted_nodes())[0])
        self.broadcast({"type": "cluster-status", "status": self.cluster.to_status()})

    def resize_abort(self) -> None:
        """Abort an in-flight resize job (api.go:1250 ResizeAbort);
        overridden by the resize subsystem when attached."""
        from pilosa_tpu.parallel.cluster import STATE_NORMAL

        self.cluster.set_state(STATE_NORMAL)
        self.broadcast({"type": "cluster-status", "status": self.cluster.to_status()})

    def note_shard_created(self, index: str, field: str, shard: int) -> None:
        """Broadcast new-shard existence after a local write created it."""
        self.broadcast(
            {"type": "create-shard", "index": index, "field": field, "shard": shard}
        )
