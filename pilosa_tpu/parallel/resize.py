"""Elastic resize: re-shard the cluster when a node joins or leaves.

Parity target: the reference's resize machinery (cluster.go:1196-1561):
the coordinator computes, per node, the set of fragments it will own
under the new topology but does not hold under the old one, along with a
source node for each (fragCombos :726, fragsDiff :684, fragSources
:784); sends every node a ResizeInstruction; nodes fetch fragment data
from their sources and ack (followResizeInstruction :1297-1411); the
cluster is RESIZING (writes and queries 405 at the API) for the
duration; on completion the coordinator broadcasts the new NORMAL
ClusterStatus and all nodes drop fragments they no longer own
(holderCleaner, holder.go:1103-1154).

TPU framing: device buffers can't be re-sharded incrementally — each
transferred fragment moves as its serialized roaring archive
(fragment.go:2436 WriteTo/ReadFrom) and is re-imported, which re-packs
it into HBM-resident tensors on the new owner (SURVEY.md §7 risk
register, checkpoint-and-reshard).
"""

from __future__ import annotations

import base64

from pilosa_tpu.parallel.cluster import (
    Node,
    STATE_NORMAL,
    STATE_RESIZING,
    TransportError,
    shard_owners,
)
from pilosa_tpu.serve.admission import tagged


class ResizeError(RuntimeError):
    pass


def plan_transfers(holder, old_ids: list[str], new_ids: list[str],
                   replica_n: int, partition_n: int,
                   hasher=None) -> dict[str, list[dict]]:
    """node id -> list of {index, field, shard, source} transfers
    (cluster.go:784 fragSources).  Source preference: the old primary,
    then old replicas, excluding nodes absent from *both* topologies."""
    old_sorted = sorted(old_ids)
    new_sorted = sorted(new_ids)
    out: dict[str, list[dict]] = {nid: [] for nid in new_sorted}
    for d in holder.schema():
        iname = d["name"]
        idx = holder.index(iname)
        if idx is None:
            continue
        for f in idx.all_fields():
            for shard in sorted(f.available_shards()):
                old_owners = shard_owners(old_sorted, iname, shard,
                                          replica_n, partition_n, hasher)
                new_owners = shard_owners(new_sorted, iname, shard,
                                          replica_n, partition_n, hasher)
                for dest in new_owners:
                    if dest in old_owners:
                        continue
                    sources = [s for s in old_owners if s != dest]
                    if not sources:
                        continue
                    out[dest].append({
                        "index": iname, "field": f.name,
                        "shard": shard, "source": sources[0],
                        "fallbacks": sources[1:],
                    })
    return out


class Resizer:
    """Coordinator-side resize job driver (cluster.go:1196 resizeJob +
    :1141 listenForJoins).  Synchronous: instructions are dispatched over
    the control plane and acked in-line; abort resets state."""

    def __init__(self, node):
        self.node = node
        self.cluster = node.cluster
        self.aborted = False

    def _broadcast_status(self) -> None:
        self.node.broadcast({"type": "cluster-status",
                             "status": self.cluster.to_status()})

    @tagged("internal")
    def run(self, add: Node | None = None,
            remove_id: str | None = None) -> dict:
        """Admit/remove a node with data movement.  Returns a summary
        {transfers: N, nodes: [...]}.  Resize control + fragment
        transfer RPC rides the internal class end to end, so a resize
        can never starve user queries."""
        c = self.cluster
        if not c.is_coordinator:
            raise ResizeError("resize must run on the coordinator")
        # atomic check-and-set: concurrent joins must serialize, or both
        # would plan against stale membership (the reference queues join
        # events on one coordinator goroutine, cluster.go:1141)
        with c._lock:
            if c.state == STATE_RESIZING:
                raise ResizeError("a resize job is already running")
            old_ids = [n.id for n in c.sorted_nodes()]
            new_ids = list(old_ids)
            if add is not None and add.id not in new_ids:
                new_ids.append(add.id)
            if remove_id is not None:
                if remove_id not in new_ids:
                    raise ResizeError(f"node not found: {remove_id}")
                new_ids.remove(remove_id)
            if sorted(new_ids) == sorted(old_ids):
                return {"transfers": 0, "nodes": new_ids}
            c.state = STATE_RESIZING

        plan = plan_transfers(self.node.holder, old_ids, new_ids,
                              c.replica_n, c.partition_n, c.hasher)
        self._broadcast_status()
        try:
            total = self._execute(plan, add, remove_id, old_ids)
        except Exception:
            # abort: revert membership-independent state, unblock writes
            # (api.go:1250 ResizeAbort path)
            c.set_state(STATE_NORMAL)
            self._broadcast_status()
            raise
        # commit the new topology
        if add is not None:
            c.add_node(add)
        removed_node = None
        if remove_id is not None:
            removed_node = c.node(remove_id)
            c.remove_node(remove_id)
            if c.coordinator_id == remove_id:
                c.set_coordinator(sorted(new_ids)[0])
        # tell the removed node it is out BEFORE the post-commit
        # broadcast (which no longer reaches it), so its background
        # loops stop pushing data at the old replicas
        if removed_node is not None:
            try:
                c.transport.send_message(removed_node,
                                         {"type": "node-removed"})
            except TransportError:
                pass
        c.set_state(STATE_NORMAL)
        c._update_cluster_state()
        self._broadcast_status()
        # propagate the coordinator's global shard availability so the
        # joiner fans queries out over shards it doesn't hold locally
        self.node.broadcast_node_status()
        # post-resize cleanup everywhere (holder.go:1126 holderCleaner)
        # — grace-deferred: an in-flight query planned under the OLD
        # topology may still read the re-homed fragments (see
        # ClusterNode.request_cleanup)
        self.node.broadcast({"type": "holder-cleanup"})
        self.node.request_cleanup()
        return {"transfers": total, "nodes": new_ids}

    def _execute(self, plan: dict[str, list[dict]], add: Node | None,
                 remove_id: str | None, old_ids: list[str]) -> int:
        """Send each node its ResizeInstruction and collect acks
        (cluster.go:1279 sendTo / :1297 followResizeInstruction)."""
        c = self.cluster
        schema = self.node.holder.schema()
        # node id -> uri for sources (the joiner isn't in the ring yet)
        uris = {n.id: n.uri for n in c.sorted_nodes()}
        if add is not None:
            uris[add.id] = add.uri
        status = c.to_status()
        if add is not None and all(n["id"] != add.id
                                   for n in status["nodes"]):
            status = dict(status)
            status["nodes"] = status["nodes"] + [add.to_dict()]
        total = 0
        for dest_id, transfers in plan.items():
            if self.aborted:
                raise ResizeError("resize aborted")
            instruction = {
                "type": "resize-instruction",
                "schema": schema,
                "transfers": transfers,
                "status": status,
                "uris": uris,
            }
            if dest_id == c.local_id:
                resp = self.node.receive_message(instruction)
            else:
                dest = c.node(dest_id) or (add if add and add.id == dest_id
                                           else None)
                if dest is None:
                    continue
                resp = c.transport.send_message(dest, instruction)
            if not resp.get("ok"):
                raise ResizeError(
                    f"resize instruction failed on {dest_id}: "
                    f"{resp.get('error')}")
            total += len(transfers)
        return total

    def abort(self) -> None:
        self.aborted = True


@tagged("internal")
def follow_resize_instruction(node, msg: dict) -> dict:
    """Destination-side: apply schema, fetch each assigned fragment (all
    views) from its source, import, ack (cluster.go:1297
    followResizeInstruction)."""
    node.holder.apply_schema(msg.get("schema", []))
    uris = msg.get("uris", {})
    peer_nodes = {n["id"]: Node.from_dict(n)
                  for n in msg.get("status", {}).get("nodes", [])}
    for t in msg.get("transfers", []):
        sources = [t["source"]] + list(t.get("fallbacks", []))
        last_err = None
        done = False
        for src_id in sources:
            src = peer_nodes.get(src_id) or Node(id=src_id,
                                                 uri=uris.get(src_id, ""))
            if src.uri == "" and src_id in uris:
                src.uri = uris[src_id]
            try:
                _fetch_fragment(node, src, t["index"], t["field"],
                                t["shard"])
                done = True
                break
            except TransportError as e:
                last_err = e
        if not done:
            return {"ok": False,
                    "error": f"no reachable source for "
                             f"{t['index']}/{t['field']}/shard "
                             f"{t['shard']}: {last_err}"}
    return {"ok": True}


def _fetch_fragment(node, src: Node, index: str, field: str,
                    shard: int) -> None:
    """Pull every view of one fragment from `src` and import it
    (http/client.go:742 RetrieveShardFromURI; the archive covers all
    views, fragment.go:2436)."""
    resp = node.cluster.transport.send_message(src, {
        "type": "fragment-views", "index": index, "field": field,
        "shard": shard,
    })
    if not resp.get("views"):
        # The source holds no data for this fragment: do NOT mark the
        # transfer done, or post-resize cleanup could delete the only
        # real copy elsewhere — fall back to another source instead.
        raise TransportError(
            f"source {src.id} has no data for {index}/{field}/shard "
            f"{shard}")
    idx = node.holder.index(index)
    f = None if idx is None else idx.field(field)
    if f is None:
        raise TransportError(f"field not found locally: {field}")
    for vname in resp["views"]:
        data_resp = node.cluster.transport.send_message(src, {
            "type": "fragment-data-b64", "index": index, "field": field,
            "view": vname, "shard": shard,
        })
        if not data_resp.get("ok", True) or "data" not in data_resp:
            raise TransportError(
                f"source {src.id} failed fragment data for "
                f"{index}/{field}/{vname}/shard {shard}: "
                f"{data_resp.get('error')}")
        data = base64.b64decode(data_resp["data"])
        view = f.create_view_if_not_exists(vname)
        frag = view.create_fragment_if_not_exists(shard)
        frag.import_roaring(data)
    f._note_shard(shard)
