"""Query executor: the full PQL op table over per-shard device kernels.

Parity target: the reference's distributed executor (executor.go).  The
shape is the same — validate, dispatch per call, map over shards, reduce —
but shard-level evaluation is TPU-native: bitmap expressions evaluate as
chains of XLA bitwise kernels over HBM-resident fragment tensors
(pilosa_tpu.ops) instead of per-container roaring loops, and TopN/GroupBy
use batched whole-matrix popcount scans instead of heap walks.

Single-node map-reduce runs shards on a thread pool (the analog of the
reference's NumCPU worker pool, executor.go:80-104).  The cluster layer
(pilosa_tpu.parallel.cluster) plugs into ``shards_for_node`` to restrict
execution to locally-owned shards, and the mesh path
(pilosa_tpu.parallel.mesh) fuses whole shard batches into single sharded
XLA programs.
"""

from __future__ import annotations

import bisect
import datetime as _dt
import heapq
import threading
import time as _time
from operator import itemgetter as _itemgetter
from concurrent.futures import (
    FIRST_COMPLETED,
    Future,
    ThreadPoolExecutor,
    wait as futures_wait,
)
from dataclasses import dataclass, replace

import numpy as np

from pilosa_tpu.models.field import FieldType
from pilosa_tpu.models.row import Row
from pilosa_tpu.parallel.cluster import (
    UNOWNED_MARKER,
    ShedByPeerError,
    TransportError,
)
from pilosa_tpu.models.timequantum import parse_time
from pilosa_tpu.models.view import VIEW_STANDARD
from pilosa_tpu.ops import bitmap as bm
from pilosa_tpu.parallel.results import (
    FieldRow,
    GroupCount,
    Pair,
    ValCount,
    sort_pairs,
)
from pilosa_tpu.pql import Call, Query, parse
from pilosa_tpu.runtime import residency as _residency
from pilosa_tpu.runtime import resultcache
from pilosa_tpu.serve import deadline as _deadline
from pilosa_tpu.serve import tenant as _tenantmod
from pilosa_tpu.serve.deadline import DeadlineExceededError
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu import faultinject as _fi
from pilosa_tpu import observe as _observe
from pilosa_tpu import stats as _stats
from pilosa_tpu import tracing


def _next_pow2(n: int) -> int:
    """Smallest power of two >= n (shape padding so batched kernels
    compile O(log) distinct programs, not one per group count)."""
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


@dataclass
class ExecOptions:
    """Per-request execution options (reference execOptions,
    executor.go:60)."""

    remote: bool = False
    exclude_row_attrs: bool = False
    exclude_columns: bool = False
    column_attrs: bool = False
    shards: list[int] | None = None
    # per-request opt-out of cross-query micro-batching (the HTTP
    # layer's ?nocoalesce=true — debugging / latency-sensitive callers)
    coalesce: bool = True
    # per-request opt-out of the generation-stamped result cache (the
    # HTTP layer's ?nocache=1 — symmetric with ?nocoalesce)
    cache: bool = True
    # per-request opt-out of streaming-ingest delta fusion (the HTTP
    # layer's ?nodelta=1 — symmetric with ?nocoalesce/?nocache): the
    # touched fragments' pending deltas are compacted up front and the
    # query runs against pure base state (a debugging escape; results
    # are bit-exact either way)
    delta: bool = True
    # per-request opt-out of the compressed container-directory
    # engine (the HTTP layer's ?nocontainers=1 — symmetric with
    # ?nocoalesce/?nocache/?nodelta): fused reads route the exact
    # dense pre-container path; results are bit-identical either way
    containers: bool = True
    # per-request opt-out of mesh-native SPMD execution (the HTTP
    # layer's ?nomesh=1 — symmetric with the other escapes): fused
    # dispatches run the exact pre-mesh single-device programs
    # (parallel/meshexec.py stays out of the launch); results are
    # byte-identical either way
    mesh: bool = True
    # per-request opt-out of the Pallas bitmap VM (the HTTP layer's
    # ?novm=1 — symmetric with ?nocontainers): coalesced sparse Count
    # batches route the pre-VM ragged/fused engines instead of the
    # one-kernel compressed megabatch (ops/tape.execute_vm); results
    # are byte-identical either way
    vm: bool = True
    # per-request opt-out of tiered residency (the HTTP layer's
    # ?notiers=1 — symmetric with the other escapes): host-tier
    # lookups miss, evictions drop instead of demoting, and misses
    # rebuild inline (runtime/residency.py pre-tier behavior); results
    # are byte-identical either way
    tiers: bool = True
    # end-to-end deadline (serve/deadline.Deadline), propagated from
    # the X-Pilosa-Deadline header; checked at translate, before each
    # per-shard map, and before reduce so expired work never reaches
    # device dispatch
    deadline: object | None = None
    # degraded-read mode (the HTTP layer's ?partial=1 / the
    # X-Pilosa-Partial header, forwarded on sub-queries like
    # ?nocache): shards whose replicas are ALL unavailable are
    # ACCOUNTED in ``missing`` instead of failing the whole query —
    # the caller surfaces missingShards/missingFraction.  The default
    # (partial=False, missing=None) keeps today's all-or-error
    # semantics on exactly the same code path.
    partial: bool = False
    missing: set | None = None
    # widest shard fan-out this request targeted (stamped by
    # _target_shards) — the denominator of missingFraction
    targeted: int = 0
    # the request's tenant id (the HTTP layer's X-Pilosa-Tenant header
    # / ?tenant= param, forwarded on node-to-node sub-queries like
    # ?nocache): installed as the thread-local tenant scope for the
    # execution, so admission quotas, result-cache soft budgets and
    # residency tier quotas all charge the right tenant.  None rides
    # the default tier; with [tenants] off it is inert.
    tenant: str | None = None


class ExecutionError(ValueError):
    pass


class ShardsUnavailableError(ExecutionError):
    """Read fan-out exhausted every replica of one or more shards.

    Structured (chaos round): ``shards`` is the sorted unavailable
    shard list and ``causes`` maps shard -> {node_id: cause} with
    cause one of ``transport`` / ``timeout`` / ``shed`` / ``breaker``
    — surfaced in the HTTP error body (503 with
    ``unavailableShards``/``causes``) and on the flight record,
    replacing the old flat "all replicas exhausted" string."""

    def __init__(self, shards, causes: dict | None = None):
        self.shards = sorted(shards)
        causes = causes or {}
        self.causes = {s: dict(causes.get(s, {})) for s in self.shards}
        head = self.shards[:8]
        detail = "; ".join(
            f"shard {s}: " + (", ".join(
                f"{n}={c}" for n, c in sorted(self.causes[s].items()))
                or "no live replica")
            for s in head)
        more = ("" if len(self.shards) <= 8
                else f" (+{len(self.shards) - 8} more)")
        super().__init__(
            f"shards {self.shards} unavailable: all replicas "
            f"exhausted{more}: {detail}")


def _failure_cause(e: BaseException) -> str:
    """Classify one replica failure for ShardsUnavailableError /
    /debug surfaces: shed (peer alive but refusing), timeout (the
    transport gave up waiting), transport (unreachable/mid-request
    death)."""
    if isinstance(e, ShedByPeerError):
        return "shed"
    s = str(e).lower()
    if "timed out" in s or "timeout" in s:
        return "timeout"
    return "transport"


class _Flight:
    """One in-flight remote shard map (original or hedge)."""

    __slots__ = ("node_id", "shards", "t0", "race", "is_hedge",
                 "hedge_attempted")

    def __init__(self, node_id: str, shards: list[int], t0: int,
                 race: "_HedgeRace | None" = None,
                 is_hedge: bool = False):
        self.node_id = node_id
        self.shards = shards
        self.t0 = t0
        self.race = race
        self.is_hedge = is_hedge
        self.hedge_attempted = False


class _HedgeRace:
    """One original flight racing its hedge re-issues.  Remote results
    are not separable per shard (a Count sub-query returns one total
    over its shard group), so the race commits a whole SIDE: the
    original, or the full set of hedge flights covering the same
    shards — first side to completely succeed wins, the loser is
    abandoned (ignored, never awaited).  Touched only by the one
    thread running the owning map loop — no lock."""

    __slots__ = ("node_id", "shards", "orig_failed", "orig_error",
                 "hedge_pending", "hedge_failed", "hedge_results",
                 "committed")

    def __init__(self, node_id: str, shards: list[int]):
        self.node_id = node_id
        self.shards = shards
        self.orig_failed = False
        self.orig_error: BaseException | None = None
        self.hedge_pending = 0
        self.hedge_failed = False
        self.hedge_results: list = []
        self.committed: str | None = None


class UnownedShardError(ExecutionError):
    """A replica write delivery targeted a shard this node does not
    own per its CURRENT membership view (reference api.go
    ErrClusterDoesNotOwnShard) — the origin's view is stale; it must
    re-resolve the owner set and retry.  In-process origins match the
    structured ``unowned`` flag; over HTTP the refusal degrades to the
    distinctive UNOWNED_MARKER token in the error string."""

    unowned = True

    def __init__(self, shard: int):
        super().__init__(
            f"{UNOWNED_MARKER}: node does not own shard {shard}")


# Sentinel call names substituted during key translation when a read-path
# key does not exist: _Empty evaluates as an empty bitmap, _Noop as a
# changed=False write (reference: missing keys yield empty rows /
# unchanged writes, executor.go:2610 translateCalls).
_EMPTY_CALL = "_Empty"
_NOOP_CALL = "_Noop"
_EMPTY_ROWS_CALL = "_EmptyRows"


class Executor:
    def __init__(self, holder, worker_pool_size: int | None = None, cluster=None):
        self.holder = holder
        self.cluster = cluster  # optional cluster layer
        self.node = None  # back-ref set by ClusterNode (shard broadcasts)
        self.stats = _stats.NOP  # injected by the server assembly
        self.logger = None
        self.long_query_time = 0.0  # seconds; 0 disables slow-query log
        self.fuse_shards = True  # master switch for fused all-shard paths
        # optional cross-query micro-batcher (parallel/coalescer.py),
        # injected by the server assembly; None = no coalescing
        self.coalescer = None
        # query flight recorder (pilosa_tpu.observe); the server
        # assembly replaces this with one carrying config/logger/stats
        self.recorder = _observe.FlightRecorder()
        # pool size defaults to CPU count (reference worker pool =
        # NumCPU, executor.go:80-104)
        import os as _os

        self.pool = ThreadPoolExecutor(
            max_workers=worker_pool_size or _os.cpu_count() or 8)
        # hedged replica reads ([cluster] hedge-* config; the server
        # assembly overwrites these): a remote shard map still in
        # flight past the peer's EWMA + k*dev latency threshold is
        # re-issued to the next replicas and the first full result
        # wins.  The fraction bound is global across queries, so the
        # counters live here under their own lock.
        self.hedge_min_samples = 8
        self.hedge_deviations = 4.0
        self.hedge_min_s = 0.02
        self.hedge_max_fraction = 0.1  # of RPC volume; <=0 disables
        self._hedge_lock = threading.Lock()
        self._hedge_rpcs = 0
        self._hedge_issued = 0
        self._hedge_wins = 0
        # partial-result accounting (?partial=1 requests / requests
        # that actually degraded) — the partial.* gauge family
        self._partial_requests = 0
        self._partial_degraded = 0

    # ------------------------------------------------------------- public

    def execute(self, index_name: str, query, shards=None, opt: ExecOptions | None = None):
        """Execute a PQL query string or Query -> list of results
        (reference executor.Execute, executor.go:113)."""
        opt = opt or ExecOptions()
        raw_query = query
        if isinstance(query, str):
            # sentinel call spellings (_Empty/_Noop/_EmptyRows) only
            # parse with remote semantics: they are the translation
            # layer's wire detail, not public surface
            query = parse(query, allow_internal=opt.remote)
        if not isinstance(query, Query):
            raise TypeError("query must be a PQL string or Query")
        idx = self.holder.index(index_name)
        if idx is None:
            raise ExecutionError(f"index not found: {index_name}")
        if opt.remote and shards:
            # receiver-side ownership gate for remote sub-queries
            # (reads AND replica writes): after an online rebalance
            # cuts a shard over, an ex-owner still holds the data for
            # a cleanup-grace window but must refuse to answer for it
            # — silently serving would hand the origin a soon-stale
            # copy the anti-entropy/dual-write machinery no longer
            # maintains here.  The structured marker lets the origin
            # fail over to the current owners.
            self._check_remote_shards_owned(idx, shards)
        if opt.partial:
            if opt.missing is None:
                # a partial request always carries its accounting set
                opt.missing = set()
            with self._hedge_lock:
                self._partial_requests += 1
        if not opt.mesh:
            # ONE fallback tick per executed ?nomesh=1 request — the
            # fused paths consult _query_mesh at several call sites
            # (staging + per-group batch fns), which must not each
            # count
            from pilosa_tpu.parallel import meshexec as _meshexec

            _meshexec.note_fallback()
        rec = None
        if self.recorder is not None and self.recorder.enabled:
            # str() on a parsed Query re-serializes the AST — only pay
            # it when a record is actually being assembled
            pql_text = (raw_query if isinstance(raw_query, str)
                        else str(raw_query))
            rec = self.recorder.begin(index_name, pql_text,
                                      trace_id=tracing.active_trace_id())
        t0 = _time.perf_counter()
        try:
            with _observe.attach(rec), \
                    _residency.no_tiers(not opt.tiers), \
                    _tenantmod.scope(opt.tenant), \
                    tracing.start_span("executor.Execute") as span, \
                    tracing.propagate(rec.trace_id
                                      if rec is not None
                                      and not span.trace_id
                                      else None):
                # the propagate fallback: under the nop tracer with no
                # inbound traceparent the record's self-generated id
                # becomes the active trace, so downstream RPCs (shard
                # map, hedges) still carry a joinable traceparent and
                # /debug/trace/{id} can assemble the cross-node tree
                span.set_tag("index", index_name)
                if rec is not None:
                    rec.tenant = opt.tenant
                    rec.remote = bool(opt.remote)
                if rec is not None:
                    # span -> record linkage: the record carries the
                    # exported trace id, the span the record id
                    if span.trace_id:
                        rec.trace_id = span.trace_id
                    span.set_tag("query.record", rec.qid)
                # Key translation happens once at the originating node,
                # never on remote re-execution (reference
                # executor.Execute, executor.go:146).
                _deadline.check(opt.deadline, "translate")
                calls = query.calls
                if not opt.remote:
                    ts = _time.perf_counter_ns()
                    calls = [self._translate_call(idx, c) for c in calls]
                    if rec is not None:
                        rec.note_stage("translate",
                                       _time.perf_counter_ns() - ts)
                results = []
                for call in calls:
                    self.stats.count_with_tags(
                        "query", 1, 1.0, [f"index:{index_name}",
                                          f"call:{call.name}"])
                    # per-op latency via the shared timing surface
                    # (exception-safe: failed calls record too)
                    tc = _time.perf_counter_ns()
                    try:
                        # implicit parenting on purpose: under the nop
                        # tracer the active span here is the propagate
                        # fallback's ContextSpan, not the bare Execute
                        # span — an explicit traceless parent would
                        # bury the trace for the whole call (map
                        # fan-out RPCs, replica writes, hint stamps)
                        with _stats.Timer(self.stats,
                                          f"execute.{call.name}"), \
                                tracing.start_span(
                                    f"executor.execute{call.name}"):
                            results.append(
                                self._execute_call(idx, call, shards, opt))
                    finally:
                        if rec is not None:
                            rec.note_stage(f"execute.{call.name}",
                                           _time.perf_counter_ns() - tc)
                if not opt.remote:
                    ts = _time.perf_counter_ns()
                    results = [
                        self._translate_result(idx, call, res)
                        for call, res in zip(calls, results)
                    ]
                    if rec is not None:
                        rec.note_stage("translateResults",
                                       _time.perf_counter_ns() - ts)
        except BaseException as e:
            if rec is not None:
                if isinstance(e, DeadlineExceededError):
                    rec.outcome = "expired"
                if isinstance(e, ShardsUnavailableError):
                    # the structured unavailability surfaces on the
                    # flight record too, not just the HTTP body
                    for s in e.shards:
                        rec.note_missing(s)
                self.recorder.publish(rec,
                                      error=f"{type(e).__name__}: {e}")
            raise
        if opt.missing:
            with self._hedge_lock:
                self._partial_degraded += 1
        if rec is not None:
            rec.result_sizes = [_observe.result_size(r) for r in results]
            self.recorder.publish(rec)
        elapsed = _time.perf_counter() - t0
        if (self.long_query_time > 0 and elapsed > self.long_query_time
                and self.logger is not None):
            # slow-query log (reference cluster.long-query-time,
            # api.go:1157); the trace id makes a logged outlier one
            # /debug/trace/{id} away
            self.logger.printf("slow query (%.3fs) trace=%s on %s: %s",
                               elapsed,
                               rec.trace_id if rec is not None else "-",
                               index_name, query)
        return results

    # ----------------------------------------------------------- dispatch

    def _execute_call(self, idx, call: Call, shards, opt: ExecOptions):
        name = call.name
        if name == _EMPTY_CALL:
            return Row()
        if name == _NOOP_CALL:
            return False
        if name == _EMPTY_ROWS_CALL:
            return []
        if name == "Set":
            return self._execute_set(idx, call, opt)
        if name == "Clear":
            return self._execute_clear(idx, call, opt)
        if name == "ClearRow":
            return self._execute_clear_row(idx, call, shards, opt)
        if name == "Store":
            return self._execute_store(idx, call, shards, opt)
        if name == "SetRowAttrs":
            return self._execute_set_row_attrs(idx, call, opt)
        if name == "SetColumnAttrs":
            return self._execute_set_column_attrs(idx, call, opt)
        if name == "Count":
            return self._execute_count(idx, call, shards, opt)
        if name == "TopN":
            return self._execute_topn(idx, call, shards, opt)
        if name == "Rows":
            return self._execute_rows(idx, call, shards, opt)
        if name == "GroupBy":
            return self._execute_group_by(idx, call, shards, opt)
        if name in ("Sum", "Min", "Max"):
            return self._execute_aggregate(idx, call, shards, opt)
        if name in ("MinRow", "MaxRow"):
            return self._execute_extreme_row(idx, call, shards, opt)
        if name == "Options":
            return self._execute_options(idx, call, shards, opt)
        # bitmap calls: Row/Union/Intersect/Difference/Xor/Not/Shift/Range
        return self._execute_bitmap_call(idx, call, shards, opt)

    # ------------------------------------------------------------ helpers

    def _target_shards(self, idx, shards, opt: ExecOptions) -> list[int]:
        if opt.shards is not None:
            out = sorted(opt.shards)
        elif shards is not None:
            out = sorted(shards)
        else:
            out = sorted(idx.available_shards())
        rec = _observe.current()
        if rec is not None:
            # the chokepoint every op's shard resolution passes through:
            # record the query's fan-out (max across calls)
            rec.note_shards(len(out))
        if opt is not None and len(out) > opt.targeted:
            # missingFraction's denominator for partial results
            opt.targeted = len(out)
        return out

    def _cluster_active(self, opt: ExecOptions | None) -> bool:
        return (
            self.cluster is not None
            and self.cluster.transport is not None
            and (opt is None or not opt.remote)
            and len(self.cluster.sorted_nodes()) > 1
        )

    @staticmethod
    def _submit_io(fn, *args):
        """Run a remote sub-query on its own thread and return a Future.
        The reference bounds only local shard work by NumCPU; per-node
        mapper goroutines are unbounded (executor.go:2517), so remote
        fan-out must never queue behind the compute pool or behind other
        nodes' sub-queries — distributed latency is max(per-node)."""
        fut = Future()
        # carry the caller's active span AND deadline into the IO
        # thread so the outbound RPC injects the right trace context
        # and re-serializes the remaining budget on the wire
        parent_span = tracing.current_span()
        dl = _deadline.current()

        def run():
            if not fut.set_running_or_notify_cancel():
                return
            try:
                with _deadline.scope(dl):
                    if parent_span is not None:
                        with tracing.start_span("executor.remoteExec",
                                                parent=parent_span):
                            fut.set_result(fn(*args))
                    else:
                        fut.set_result(fn(*args))
            except BaseException as e:  # delivered via fut.result()
                fut.set_exception(e)

        threading.Thread(target=run, daemon=True).start()
        return fut

    def _local_map(self, fn, shards, deadline=None):
        rec = _observe.current()
        notiers = _residency.tiers_off_scope()
        tenant = _tenantmod.current()
        if rec is not None or deadline is not None or _fi.armed \
                or notiers or tenant is not None:
            # re-attach the flight record on the pool workers so their
            # kernel launches tick it, time each shard's evaluation,
            # and bail before a shard whose deadline already expired —
            # expired work must never reach device dispatch.  The
            # ?notiers scope and the tenant identity re-install the
            # same way the record does: worker threads must honor the
            # caller's escape and charge the caller's tenant.
            inner = fn

            def fn(shard, _inner=inner, _rec=rec, _dl=deadline,
                   _nt=notiers, _ten=tenant):
                if _fi.armed:
                    # failpoint: the production per-shard map
                    _fi.hit("executor.map_shard")
                if _dl is not None and _dl.expired():
                    raise DeadlineExceededError(
                        f"deadline expired before map of shard {shard}")
                with _residency.no_tiers(_nt), _tenantmod.scope(_ten):
                    if _rec is None:
                        return _inner(shard)
                    t0 = _time.perf_counter_ns()
                    with _observe.attach(_rec):
                        out = _inner(shard)
                    _rec.note_shard(shard, _time.perf_counter_ns() - t0)
                    return out

        if len(shards) <= 1:
            return [fn(s) for s in shards]
        return list(self.pool.map(fn, shards))

    def _map_shards(self, fn, shards, idx=None, call=None, opt=None, adapt=None,
                    remote_call=None, local_batch_fn=None):
        """Map over shards and return the flat list of per-shard/per-node
        partials.  Single-node: worker-pool map (reference mapperLocal,
        executor.go:2561).  Clustered (and not already a remote
        re-execution): group shards by owner node, run local shards on
        the pool, forward each remote group as one PQL sub-query, and on
        node failure re-map its shards onto replicas until owners are
        exhausted (reference mapReduce, executor.go:2455-2514).  `adapt`
        converts one remote result into a list of local-partial-shaped
        values.  `local_batch_fn(shards) -> partials` replaces the
        per-shard pool for the locally-owned group when the call has a
        fused all-shard evaluation (remote nodes fuse on their own side,
        since remote re-execution is non-clustered)."""
        rec = _observe.current()
        dl = opt.deadline if opt is not None else None
        _deadline.check(dl, "map")
        t_map = _time.perf_counter_ns() if rec is not None else 0
        try:
            partials = self._map_shards_inner(
                fn, shards, idx, call, opt, adapt, remote_call,
                local_batch_fn, rec)
            # the reduce boundary: partials whose deadline died in
            # flight are dropped here, never folded
            _deadline.check(dl, "reduce")
            return partials
        finally:
            if rec is not None:
                # the map stage boundary (reference mapReduce,
                # executor.go:2455); the enclosing execute.<Call> stage
                # minus this is the reduce side
                rec.note_stage("map", _time.perf_counter_ns() - t_map)

    def _map_shards_inner(self, fn, shards, idx, call, opt, adapt,
                          remote_call, local_batch_fn, rec):
        dl = opt.deadline if opt is not None else None
        if not (self._cluster_active(opt) and idx is not None and call is not None
                and adapt is not None):
            return self._local_map(fn, shards, deadline=dl)
        cluster = self.cluster
        pql = str(call if remote_call is None else remote_call)
        partials = []
        tried: dict[int, set] = {s: set() for s in shards}
        causes: dict[int, dict] = {}  # shard -> {node_id: cause}
        pending = cluster.shards_by_node(idx.name, shards)
        inflight: dict = {}  # future -> _Flight

        def submit(node_id, node_shards, race=None, is_hedge=False):
            extra = {}
            if opt is not None and not opt.cache:
                # forward the origin's ?nocache=1: peers must do a
                # real execution too, not answer from their
                # per-shard result caches
                extra["nocache"] = True
            if opt is not None and not opt.delta:
                # forward ?nodelta=1: peers compact their own
                # pending deltas and run against pure base too
                extra["nodelta"] = True
            if opt is not None and not opt.containers:
                # forward ?nocontainers=1: peers route their own
                # fused reads through the dense pre-container path
                extra["nocontainers"] = True
            if opt is not None and not opt.mesh:
                # forward ?nomesh=1: peers run their own fused
                # dispatches on the pre-mesh single-device programs
                extra["nomesh"] = True
            if opt is not None and not opt.vm:
                # forward ?novm=1: peers route their own coalesced
                # sparse reads through the pre-VM engines too
                extra["novm"] = True
            if opt is not None and not opt.tiers:
                # forward ?notiers=1: peers bypass their own tiered
                # residency too (inline rebuilds, drop-not-demote)
                extra["notiers"] = True
            if opt is not None and opt.partial:
                # forward ?partial=1: degraded-read semantics ride
                # sub-queries like the other per-request escapes
                extra["partial"] = True
            if opt is not None and opt.tenant:
                # forward the tenant id: the peer's admission gate,
                # result cache and residency tiers must charge the
                # SAME tenant the origin did (exactly like ?nocache)
                extra["tenant"] = opt.tenant
            if extra:
                fut = self._submit_io(
                    lambda n, i, p, s, _e=extra:
                    cluster.transport.query_node(n, i, p, s, **_e),
                    cluster.node(node_id), idx.name, pql,
                    node_shards,
                )
            else:
                fut = self._submit_io(
                    cluster.transport.query_node,
                    cluster.node(node_id), idx.name, pql, node_shards,
                )
            fl = _Flight(node_id, node_shards,
                         _time.perf_counter_ns(),
                         race=race, is_hedge=is_hedge)
            inflight[fut] = fl

            def _settle(f, _fl=fl):
                # Runs on the flight's IO thread the moment it
                # resolves — whether the map loop processes it, a
                # settled race purged it, or an exhaustion error
                # unwound with it still in the air — so breakers and
                # the latency EWMA ALWAYS learn the outcome.  Without
                # this, a hedged-over HALF_OPEN trial would never
                # resolve its probe and the breaker would wedge
                # refusing until a heartbeat probe happened by.
                try:
                    f.result()
                except ShedByPeerError:
                    # a shed is proof of life: never a breaker failure
                    cluster.note_peer_success(_fl.node_id)
                except TransportError:
                    cluster.note_peer_failure(_fl.node_id)
                except BaseException:  # noqa: BLE001 — deadline &c.:
                    pass  # says nothing about the PEER either way
                else:
                    cluster.note_peer_success(
                        _fl.node_id,
                        (_time.perf_counter_ns() - _fl.t0) / 1e9)

            fut.add_done_callback(_settle)
            with self._hedge_lock:
                self._hedge_rpcs += 1

        def fail_shards(node_shards, node_id, err, cause):
            """Fail ``node_shards`` over from ``node_id`` onto their
            next replicas; shards with no replica left are ACCOUNTED
            (?partial=1) or raised as a structured
            ShardsUnavailableError carrying the shard list and the
            per-replica causes collected along the way."""
            exhausted = []
            for s in node_shards:
                tried[s].add(node_id)
                causes.setdefault(s, {})[node_id] = cause
                nxt = cluster.next_replica(idx.name, s, tried[s])
                if nxt is None:
                    exhausted.append(s)
                else:
                    pending.setdefault(nxt.id, []).append(s)
            if not exhausted:
                return
            if (opt is not None and opt.partial
                    and opt.missing is not None):
                for s in exhausted:
                    opt.missing.add(s)
                    if rec is not None:
                        rec.note_missing(s)
                return
            if isinstance(err, ShedByPeerError):
                # every replica SHED (admission gates saturated
                # cluster-wide): transient overload, not missing data
                # — let it surface as 503 + Retry-After, never the 400
                # an ExecutionError maps to
                raise err
            raise ShardsUnavailableError(exhausted, causes)

        def purge_race(race):
            """Abandon (cancel-or-ignore) every still-inflight flight
            of a settled race: the loser's IO thread finishes on its
            own; its result is dropped.  Never await a loser — waiting
            out a slow peer is exactly what hedging exists to avoid."""
            for f2 in [f2 for f2, fl2 in inflight.items()
                       if fl2.race is race]:
                inflight.pop(f2)

        def try_hedge(fl):
            """Race ``fl``'s shards on their next replicas.  A remote
            result is one value for the whole shard group, so the
            hedge must cover EVERY shard of the flight (each on a live
            next replica) or not issue at all; the global fraction
            bound keeps hedges from ever exceeding hedge-max-fraction
            of RPC volume."""
            fl.hedge_attempted = True
            with self._hedge_lock:
                if (self._hedge_issued + 1
                        > self.hedge_max_fraction * self._hedge_rpcs):
                    return
            groups: dict[str, list[int]] = {}
            for s in fl.shards:
                nxt = cluster.next_replica(idx.name, s,
                                           tried[s] | {fl.node_id})
                if nxt is None or cluster.breaker_open(nxt.id):
                    return
                groups.setdefault(nxt.id, []).append(s)
            race = _HedgeRace(fl.node_id, fl.shards)
            race.hedge_pending = len(groups)
            fl.race = race
            for hnode_id, hshards in groups.items():
                submit(hnode_id, hshards, race=race, is_hedge=True)
            with self._hedge_lock:
                self._hedge_issued += 1
            if rec is not None:
                rec.hedged += 1
            if _observe.journal_on:
                _observe.emit("hedge.fired", node=fl.node_id,
                              shards=len(fl.shards),
                              replicas=sorted(groups))

        while pending or inflight:
            # fan out every remote group concurrently, then run local
            # shards inline while the remotes are in flight — distributed
            # latency is max(per-node), not sum (executor.go:2517 mapper
            # goroutines)
            for node_id in [k for k in list(pending) if k != cluster.local_id]:
                node_shards = pending.pop(node_id)
                if not cluster.peer_allows(node_id):
                    # breaker open: fast-fail onto the next replica
                    # without paying the transport timeout
                    fail_shards(node_shards, node_id,
                                TransportError(
                                    f"circuit breaker open for peer "
                                    f"{node_id}"),
                                "breaker")
                    continue
                submit(node_id, node_shards)
            if cluster.local_id in pending:
                local_shards = pending.pop(cluster.local_id)
                t_loc = _time.perf_counter_ns()
                _deadline.check(dl, "local map")
                if local_batch_fn is not None and len(local_shards) > 1:
                    partials.extend(local_batch_fn(local_shards))
                else:
                    partials.extend(self._local_map(fn, local_shards,
                                                    deadline=dl))
                if rec is not None:
                    rec.note_node("local",
                                  _time.perf_counter_ns() - t_loc,
                                  len(local_shards))
            if not inflight:
                continue
            # hedge pass: an original flight past its per-peer latency
            # threshold (EWMA + k*dev, floored) races its shards on
            # the next replicas; flights below threshold bound the
            # wait so the check re-runs when the soonest one crosses
            timeout = None
            if self.hedge_max_fraction > 0:
                now = _time.perf_counter_ns()
                soonest = None
                for fl in list(inflight.values()):
                    if (fl.race is not None or fl.is_hedge
                            or fl.hedge_attempted):
                        continue
                    thr = self._hedge_threshold_s(fl.node_id)
                    if thr is None:
                        continue
                    due = fl.t0 + int(thr * 1e9)
                    if now >= due:
                        try_hedge(fl)
                    elif soonest is None or due < soonest:
                        soonest = due
                if soonest is not None:
                    timeout = max(0.001, (soonest - now) / 1e9)
            done, _ = futures_wait(list(inflight), timeout=timeout,
                                   return_when=FIRST_COMPLETED)
            for fut in done:
                fl = inflight.pop(fut, None)
                if fl is None:
                    continue  # purged loser of a settled race
                try:
                    res = fut.result()
                except Exception as te:
                    if isinstance(te, TransportError):
                        # breaker/EWMA feedback already ran in the
                        # flight's _settle callback
                        cause = _failure_cause(te)
                    elif refusal_is_unowned(te):
                        # the peer answered (alive) but refused the
                        # sub-query as non-owner: an online rebalance
                        # cut the shards over and its view is fresher
                        # than ours — fail over onto the current
                        # owners without feeding the peer's breaker
                        te = TransportError(str(te))
                        cause = "unowned"
                    else:
                        raise
                    race = fl.race
                    if race is None:
                        fail_shards(fl.shards, fl.node_id, te, cause)
                        continue
                    if fl.is_hedge:
                        race.hedge_pending -= 1
                        race.hedge_failed = True
                        for s in fl.shards:
                            tried[s].add(fl.node_id)
                            causes.setdefault(s, {})[fl.node_id] = cause
                        if (race.committed is None and race.orig_failed
                                and race.hedge_pending == 0):
                            # both sides dead: normal failover for the
                            # original shard set
                            race.committed = "failed"
                            fail_shards(race.shards, race.node_id,
                                        race.orig_error,
                                        _failure_cause(race.orig_error))
                    else:
                        race.orig_failed = True
                        race.orig_error = te
                        if (race.committed is None and race.hedge_failed
                                and race.hedge_pending == 0):
                            race.committed = "failed"
                            fail_shards(fl.shards, fl.node_id, te,
                                        cause)
                        # hedge side still pending: wait for it
                    continue
                lat_ns = _time.perf_counter_ns() - fl.t0
                race = fl.race
                if race is None:
                    if rec is not None:
                        rec.note_node(fl.node_id, lat_ns,
                                      len(fl.shards))
                    partials.extend(adapt(res[0]))
                    continue
                if fl.is_hedge:
                    race.hedge_pending -= 1
                    race.hedge_results.append((fl, res))
                    if (race.committed is None and not race.hedge_failed
                            and race.hedge_pending == 0):
                        # the hedge side produced the full shard set
                        # first: commit it, abandon the original
                        race.committed = "hedge"
                        for hfl, hres in race.hedge_results:
                            if rec is not None:
                                rec.note_node(
                                    hfl.node_id,
                                    _time.perf_counter_ns() - hfl.t0,
                                    len(hfl.shards))
                            partials.extend(adapt(hres[0]))
                        with self._hedge_lock:
                            self._hedge_wins += 1
                        if rec is not None:
                            rec.hedge_wins += 1
                            # the abandoned original is the hedge
                            # loser: note who and how long its side
                            # had been in flight when the race settled
                            # — the /debug/trace/{id} tree shows the
                            # loser's side from this
                            now_ns = _time.perf_counter_ns()
                            for fl2 in inflight.values():
                                if fl2.race is race:
                                    rec.hedge_losers.append(
                                        (fl2.node_id,
                                         now_ns - fl2.t0))
                        if _observe.journal_on:
                            _observe.emit(
                                "hedge.won", side="hedge",
                                winner=sorted({hfl.node_id for hfl, _
                                               in race.hedge_results}),
                                losers=[race.node_id])
                        purge_race(race)
                else:
                    if race.committed is None:
                        race.committed = "orig"
                        now_ns = _time.perf_counter_ns()
                        losers = sorted({fl2.node_id
                                         for fl2 in inflight.values()
                                         if fl2.race is race})
                        if rec is not None:
                            rec.note_node(fl.node_id, lat_ns,
                                          len(fl.shards))
                            for fl2 in inflight.values():
                                if fl2.race is race:
                                    rec.hedge_losers.append(
                                        (fl2.node_id,
                                         now_ns - fl2.t0))
                        partials.extend(adapt(res[0]))
                        if _observe.journal_on:
                            _observe.emit("hedge.won", side="orig",
                                          winner=fl.node_id,
                                          losers=losers)
                        purge_race(race)
        return partials

    def _hedge_threshold_s(self, node_id: str) -> float | None:
        """The elapsed time past which a flight to ``node_id`` should
        hedge, or None while the peer has too few latency samples for
        the EWMA to mean anything."""
        ewma, dev, n = self.cluster.peer_latency(node_id)
        if n < self.hedge_min_samples:
            return None
        return max(self.hedge_min_s,
                   ewma + self.hedge_deviations * dev)

    @staticmethod
    def _rc_fill_ok(opt: ExecOptions | None) -> bool:
        """Partial results never enter the result cache: once this
        request has accounted a missing shard, every fill it would
        perform is suppressed (probes/hits stay — serving a COMPLETE
        cached value to a degraded request is strictly better than
        recomputing a partial one)."""
        return opt is None or not opt.missing

    def publish_chaos_gauges(self, stats) -> None:
        """hedge.* / partial.* gauge families for /metrics and
        /debug/vars — published unconditionally (zeros on a clean
        server) so the families are scrape-visible before any fault."""
        with self._hedge_lock:
            stats.gauge("hedge.rpcs", self._hedge_rpcs)
            stats.gauge("hedge.issued", self._hedge_issued)
            stats.gauge("hedge.wins", self._hedge_wins)
            stats.gauge("partial.requests", self._partial_requests)
            stats.gauge("partial.degraded", self._partial_degraded)

    def _field(self, idx, name: str):
        f = idx.field(name)
        if f is None:
            raise ExecutionError(f"field not found: {name}")
        return f

    @staticmethod
    def _np_words(words):
        return None if words is None else np.asarray(words)

    # ----------------------------------------------------- bitmap queries

    def _validate_call_fields(self, idx, call: Call) -> None:
        """Eagerly check referenced fields exist, even when the shard set
        is empty (the reference surfaces ErrFieldNotFound from the shard
        fn; with zero shards we must check up front)."""
        if call.name in ("Row", "Range"):
            cond = call.condition_arg()
            if cond is not None:
                self._field(idx, cond[0])
            else:
                self._field(idx, call.field_arg())
        for child in call.children:
            self._validate_call_fields(idx, child)

    # ------------------------------------------------ fused all-shard path

    def _fused_supported(self, idx, call: Call) -> bool:
        """True when the bitmap tree can evaluate as ONE stacked device
        computation over all shards: plain standard-view Row leaves,
        time-range Rows, and BSI condition rows, combined with
        Union/Intersect/Difference/Xor/Not/Shift."""
        name = call.name
        if name == "Row":
            cond = call.condition_arg()
            if cond is not None:
                # BSI condition rows fuse via the stacked range kernels
                fname, condition = cond
                f = idx.field(fname)
                if f is None or f.options.type != FieldType.INT:
                    return False
                if condition.op == "><":
                    v = condition.value
                    return (isinstance(v, list) and len(v) == 2
                            and all(isinstance(x, int)
                                    and not isinstance(x, bool) for x in v))
                if condition.value is None:
                    return condition.op == "!="
                return (isinstance(condition.value, int)
                        and not isinstance(condition.value, bool))
            try:
                fname = call.field_arg()
            except ValueError:
                return False
            v = call.args.get(fname)
            if not isinstance(v, int) or isinstance(v, bool):
                return False
            f = idx.field(fname)
            if f is None:
                return False
            if "from" in call.args or "to" in call.args:
                # time-range Row: the cover unions host-side into one
                # cached stack, so the cap only bounds the generation
                # tuple the cache must compare per hit
                if not f.time_quantum:
                    return False
                views = self._time_range_views(f, call)
                return views is not None and len(views) <= 256
            o = f.options
            return not (o.type == FieldType.INT
                        or (o.type == FieldType.TIME and o.no_standard_view))
        if name == "Not":
            return (len(call.children) == 1
                    and idx.existence_field() is not None
                    and self._fused_supported(idx, call.children[0]))
        if name == "Shift":
            n = call.int_arg("n")
            return (len(call.children) == 1
                    and (n is None or n >= 0)
                    and self._fused_supported(idx, call.children[0]))
        if name in ("Union", "Intersect", "Difference", "Xor"):
            return bool(call.children) and all(
                self._fused_supported(idx, c) for c in call.children)
        return False

    def _fuse_eligible(self, idx, shards, call: Call | None = None,
                       extra: bool = True) -> bool:
        """The shared precondition of every fused all-shard dispatch:
        fusion enabled, a real multi-shard batch, any op-specific
        `extra` condition, and (when the op carries a bitmap tree) the
        tree being stack-evaluable."""
        return (self.fuse_shards and len(shards) > 1 and extra
                and (call is None or self._fused_supported(idx, call)))

    def _time_range_views(self, f, call: Call) -> list[str] | None:
        """The time views covering a Row(from=, to=) query — the same
        cover and clamping as the per-shard path (f.row_time /
        _clamp_to_views); None when the range is malformed.  Runs once
        for the support check and once per evaluation; the expensive
        part (the view-name scan) is memoized on the field."""
        from pilosa_tpu.models.timequantum import views_by_time_range

        from_arg = call.args.get("from")
        to_arg = call.args.get("to")
        try:
            start = (parse_time(from_arg) if from_arg is not None
                     else _dt.datetime(1, 1, 1))
            end = (parse_time(to_arg) if to_arg is not None
                   else _dt.datetime(9999, 1, 1))
        except (ValueError, TypeError, OverflowError, OSError):
            # int timestamps can overflow fromtimestamp (platform time_t)
            return None
        start, end = self._clamp_to_views(f, start, end)
        return ([] if start >= end
                else list(views_by_time_range(VIEW_STANDARD, start, end,
                                              f.time_quantum)))

    def _fused_expr(self, idx, call: Call, shards: tuple[int, ...],
                    use_delta: bool = True):
        """Stage a supported tree for ONE-launch evaluation: returns
        ``(shape, leaves)`` where ``shape`` is the canonical structure
        key (row ids and values erased into leaf slots — distinct rows
        share a compiled program) and ``leaves`` the operand stacks, for
        ops.expr.  Leaf staging is the cached stack builders
        (device_row_stack & friends); no compute dispatches here beyond
        what BSI range leaves inherently cost.

        ``use_delta=False`` is the ?nodelta=1 escape: pending delta
        planes on the touched fragments are compacted up front and
        every leaf stays a plain base leaf."""
        leaves: list = []
        shape = self._fused_shape(idx, call, shards, leaves, use_delta)
        return shape, tuple(leaves)

    def _fused_row_leaf(self, f, row_id, shards: tuple[int, ...],
                        leaves: list, use_delta: bool):
        """One standard-view row leaf, delta-aware: the base stack is
        resident under its base token (delta writes don't evict it);
        when a pending delta touches this row in any fragment, the
        overlay stacks join as ``dfuse`` operands — staged BEFORE the
        base stack, so a compaction racing the two reads can only
        double-apply the (idempotent) overlay, never drop it."""
        if not use_delta:
            f.flush_deltas(shards)
            ds = None
        else:
            ds = f.device_delta_stacks(row_id, shards)
        leaves.append(f.device_row_stack(row_id, shards))
        shape = ("leaf", len(leaves) - 1)
        if ds is not None:
            leaves.append(ds[0])
            si = len(leaves) - 1
            leaves.append(ds[1])
            shape = ("dfuse", shape, ("leaf", si), ("leaf", len(leaves) - 1))
            rec = _observe.current()
            if rec is not None:
                rec.note_delta(1)
        return shape

    def _fused_shape(self, idx, call: Call, shards: tuple[int, ...],
                     leaves: list, use_delta: bool = True):
        name = call.name
        if name == "Row":
            cond = call.condition_arg()
            if cond is not None:
                fname, condition = cond
                value = (condition.int_slice_value()
                         if condition.op == "><" else condition.value)
                leaves.append(idx.field(fname).device_range_stack(
                    condition.op, value, shards))
                return ("leaf", len(leaves) - 1)
            fname = call.field_arg()
            f = idx.field(fname)
            if "from" in call.args or "to" in call.args:
                # time-range Row: ONE cached stack holding the
                # host-side union over the covering views (f.row_time's
                # union, batched across shards).  Delta overlays apply
                # inside the builder (effective reads; token carries
                # the delta seq) — no dfuse leaves needed.
                views = self._time_range_views(f, call) or []
                leaves.append(f.device_time_row_stack(
                    call.args[fname], shards, tuple(views)))
                return ("leaf", len(leaves) - 1)
            # arg is a plain int row id (bool literals were excluded by
            # _fused_supported)
            return self._fused_row_leaf(f, call.args[fname], shards,
                                        leaves, use_delta)
        if name in ("Union", "Intersect", "Difference", "Xor"):
            op = {"Union": "or", "Intersect": "and",
                  "Difference": "andnot", "Xor": "xor"}[name]
            return (op, *(self._fused_shape(idx, c, shards, leaves,
                                            use_delta)
                          for c in call.children))
        if name == "Not":
            exist = self._fused_row_leaf(idx.existence_field(), 0,
                                         shards, leaves, use_delta)
            return ("not", exist,
                    self._fused_shape(idx, call.children[0], shards,
                                      leaves, use_delta))
        if name == "Shift":
            n = call.int_arg("n")
            # per-shard semantics batch directly: bits shift within
            # each shard's row and drop at the shard edge, exactly as
            # the per-shard path does (executor.go:1730)
            return ("shift", 1 if n is None else n,
                    self._fused_shape(idx, call.children[0], shards,
                                      leaves, use_delta))
        raise ExecutionError(f"unsupported fused call: {name}")

    def _fused_eval(self, idx, call: Call, shards: tuple[int, ...],
                    use_delta: bool = True, mesh=None):
        """Evaluate a supported tree -> uint32 [n_shards, words] device
        stack, as ONE compiled program over the leaf stacks (ops.expr) —
        tree depth no longer multiplies the launch count, the dominant
        win when device dispatch has real latency (TPU behind an RPC
        boundary; the 20 us dispatch floor of VERDICT round 5).

        ``mesh`` (``_query_mesh``) routes the shard_map program so the
        one launch spans every mesh device; None is the pre-mesh
        single-device program (?nomesh=1 / [mesh] disabled)."""
        from pilosa_tpu.ops import expr

        shape, leaves = self._fused_expr(idx, call, shards, use_delta)
        return expr.evaluate(shape, leaves, mesh=mesh)

    @staticmethod
    def _query_mesh(opt: ExecOptions | None):
        """The device mesh this request's fused dispatches run under:
        the active [mesh] layout, or None for ?nomesh=1 (counted as a
        mesh fallback) and whenever the mesh cannot activate."""
        from pilosa_tpu.parallel import meshexec

        return meshexec.query_mesh(opt is None or opt.mesh)

    # ------------------------------------------- result cache (read paths)

    def _rc_collect_gens(self, f, view_name: str,
                         shards: tuple[int, ...], out: dict) -> None:
        """Record the invalidation stamp for one (field, view) pair
        over the shard set: the aggregate ``(count, sum_gen, sum_seq,
        sum_uid, max_uid)`` of the participating fragments' generation
        tokens — ``(base_gen, delta_seq)`` per fragment, the streaming-
        ingest extension (pilosa_tpu.ingest).

        The aggregate is change-DETECTING, not just change-likely,
        because of monotonicity invariants: a surviving fragment's
        ``_gen`` only ever increases (every base mutation and every
        compaction bumps it), ``_delta_seq`` only ever increases (every
        delta-landing write bumps it; compaction leaves it alone — so
        an entry filled against base ⊕ delta stays valid until *its*
        fragment's delta actually changes, and a compaction costs one
        conservative miss, not an eviction storm), and ``_uid`` comes
        from a process-global increasing counter, so a newly created
        fragment's uid exceeds every uid that ever existed.  Case
        analysis between fill and probe: any fragment CREATION (incl.
        a resize/restore replacement) raises ``max_uid`` past the old
        all-time high; any DELETION without a creation changes
        ``count``; any MUTATION of a surviving fragment raises
        ``sum_gen`` or ``sum_seq`` (which nothing can lower — resets
        only occur via replacement, caught by ``max_uid``).  So every
        state change flips at least one component, while an unchanged
        view reproduces the stamp exactly.

        Memoized per (field, view): ``Intersect(Row(f=a), Row(f=b))``
        touches the same view twice but needs one stamp.  The single
        pass is what keeps the 0%-hit-rate probe within its <1% budget
        at wide shard counts (bench.py extras.resultcache): the common
        fully-populated case batches all dict lookups into one C-level
        ``itemgetter`` call (~35% cheaper than per-shard ``.get`` at
        256 shards on the bench box), falling back to the filtering
        loop only when some shard has no fragment."""
        mkey = (id(f), view_name)
        if mkey in out:
            return
        view = None if f is None else f.view(view_name)
        if view is None:
            out[mkey] = 0
            return
        frags = view.fragments
        fs = None
        if len(shards) > 1:
            try:
                fs = _itemgetter(*shards)(frags)
            except KeyError:
                fs = None
        if fs is None:
            g = frags.get
            fs = [fr for s in shards if (fr := g(s)) is not None]
        sg = sq = su = mu = 0
        for fr in fs:
            u = fr._uid
            sg += fr._gen
            sq += fr._delta_seq
            su += u
            if u > mu:
                mu = u
        out[mkey] = (len(fs), sg, sq, su, mu)

    def _rc_sig(self, idx, call: Call, shards: tuple[int, ...],
                gens_out: list):
        """Canonical identity of one fused-supported bitmap tree: the
        expression shape with leaf identities (field, view, row /
        op+value) substituted at the slots — distinct queries over the
        same shape get distinct keys, unlike the coalescer's value-
        erased bucket key.  Collects every participating fragment's
        generation token into ``gens_out``; the caller captures this
        stamp BEFORE any fragment data is read (resultcache
        stamp-before-read discipline — the reverse order could stamp
        fresh generations onto stale data)."""
        name = call.name
        if name == "Row":
            cond = call.condition_arg()
            if cond is not None:
                fname, condition = cond
                f = idx.field(fname)
                self._rc_collect_gens(f, f.bsi_view_name, shards,
                                      gens_out)
                value = (condition.int_slice_value()
                         if condition.op == "><" else condition.value)
                if isinstance(value, list):
                    value = tuple(value)
                return ("range", fname, condition.op, value)
            fname = call.field_arg()
            f = idx.field(fname)
            if "from" in call.args or "to" in call.args:
                # the covering views are part of the identity: a new
                # time view (first write into a fresh quantum) changes
                # the cover, so the old entry simply stops being
                # addressed
                views = tuple(self._time_range_views(f, call) or ())
                for vn in views:
                    self._rc_collect_gens(f, vn, shards, gens_out)
                return ("time", fname, call.args[fname], views)
            self._rc_collect_gens(f, VIEW_STANDARD, shards, gens_out)
            return ("row", fname, call.args[fname])
        if name in ("Union", "Intersect", "Difference", "Xor"):
            return (name, *(self._rc_sig(idx, c, shards, gens_out)
                            for c in call.children))
        if name == "Not":
            ef = idx.existence_field()
            self._rc_collect_gens(ef, VIEW_STANDARD, shards, gens_out)
            return ("not", ef.name,
                    self._rc_sig(idx, call.children[0], shards,
                                 gens_out))
        if name == "Shift":
            n = call.int_arg("n")
            return ("shift", 1 if n is None else n,
                    self._rc_sig(idx, call.children[0], shards,
                                 gens_out))
        raise ExecutionError(f"uncacheable call: {name}")

    def _rc_probe(self, idx, kind: str, shards: tuple[int, ...],
                  opt: ExecOptions | None, tree: Call | None = None,
                  extra=None, gen_fields=()):
        """(cache, key, gens) for one fused read, or None when caching
        is off (process config or the request's ?nocache=1) or the
        tree has no canonical signature.  ``extra`` joins the key
        (e.g. the TopN field and truncation args); ``gen_fields`` is
        (field, view_name) pairs whose fragments participate beyond
        the tree leaves (e.g. the scanned TopN matrix).  Stamps the
        key digest onto the active flight record so every record
        carries its cacheKey, hit or miss.

        ``?nodelta=1`` bypasses the probe too: its contract is an
        up-front compaction and a REAL pure-base read — a cached value
        (bit-identical, but filled through the delta path) would
        short-circuit the escape into a no-op whenever the stamp
        hasn't moved."""
        rc = resultcache.cache()
        if not rc.enabled or (opt is not None
                              and not (opt.cache and opt.delta)):
            return None
        gens_out: dict = {}
        try:
            sig = (None if tree is None
                   else self._rc_sig(idx, tree, shards, gens_out))
            for f, vn in gen_fields:
                # gen_fields means a whole-matrix read (TopN refresh,
                # GroupBy Rows scan), and those merge pending deltas
                # during the read — merge BEFORE stamping instead, or
                # the fill carries pre-merge generations our own flush
                # just invalidated (dead on arrival: the next identical
                # query would re-execute instead of hitting)
                f.flush_deltas(shards)
                self._rc_collect_gens(f, vn, shards, gens_out)
        except (ExecutionError, ValueError, KeyError, TypeError,
                AttributeError):
            return None
        # the active placement flavor joins the key (PR 12 follow-up):
        # a [mesh] toggle or axis resize must not serve fills staged
        # under the previous device layout — and when the operator
        # toggles BACK, the old flavor's still-generation-valid
        # entries become warm again instead of having been overwritten
        from pilosa_tpu.parallel import meshexec as _meshexec

        placement = _meshexec.placement_token(
            opt is None or opt.mesh)
        key = resultcache.Key(
            (self.holder.uid, idx.name, kind, sig, extra, shards,
             placement))
        rec = _observe.current()
        if rec is not None:
            rec.cache_key = resultcache.key_digest(key)
        # dict values in traversal (insertion) order — deterministic
        # per shape, so fill and probe stamps always align slot-wise
        return rc, key, tuple(gens_out.values())

    @staticmethod
    def _rc_mark_hit() -> None:
        rec = _observe.current()
        if rec is not None:
            rec.cached = True
            rec.note_path("cached")

    @staticmethod
    def _rc_wait(opt) -> float:
        """Single-flight wait budget for a cache probe: never park a
        query on another reader's in-progress fill beyond its own
        deadline (the deadline checks run after the probe returns, so
        an uncapped wait could hold an admission slot 10x past a
        short budget just to report expiry)."""
        dl = None if opt is None else getattr(opt, "deadline", None)
        if dl is None:
            return resultcache.FLIGHT_WAIT_S
        return max(0.0, min(resultcache.FLIGHT_WAIT_S, dl.remaining()))

    def _execute_bitmap_call(self, idx, call: Call, shards, opt: ExecOptions) -> Row:
        self._validate_call_fields(idx, call)
        shards = self._target_shards(idx, shards, opt)
        row = Row()

        fused_ok = self._fuse_eligible(idx, shards, call)

        def batch_fn(group):
            # probe the result cache FIRST (stamp captured before any
            # fragment read); a hit skips the device entirely
            g = tuple(group)
            probe = self._rc_probe(idx, "row", g, opt, tree=call)
            if probe is not None:
                rc, key, gens = probe
                hit, val = rc.get(key, gens, self._rc_wait(opt))
                if hit:
                    self._rc_mark_hit()
                    # copies both ways (fill and hit): cached words
                    # must never alias a Row a caller may mutate
                    return [(s, w.copy()) for s, w in val]
            # sparse trees route the compressed container engine
            # (ops/containers.py): one launch over the pooled
            # directory-matched containers, scattered back to dense
            # per-shard words here
            from pilosa_tpu.ops import containers as _containers

            m = self._query_mesh(opt)
            cplan = _containers.plan_fused(self, idx, call, g, opt,
                                           counts=False)

            def _dispatch():
                # the fused Row launch (dense or container-gather),
                # under the shared RESOURCE_EXHAUSTED evict-and-retry
                if cplan is not None:
                    return cplan.row_words(mesh=m)
                # copies: a view would pin the whole stack in memory
                # for as long as one sparse segment lives
                stack = np.asarray(self._fused_eval(idx, call, g,
                                                    use_delta=opt.delta,
                                                    mesh=m))
                return [(s, stack[i].copy())
                        for i, s in enumerate(group)
                        if stack[i].any()]

            partials = _residency.run_with_oom_retry(_dispatch)
            if probe is not None and self._rc_fill_ok(opt):
                value = [(s, w.copy()) for s, w in partials]
                rc.put(key, gens, value,
                       sum(w.nbytes for _, w in value) + 32 * len(value))
            return partials

        rec = _observe.current()
        if rec is not None:
            rec.note_path("fused" if fused_ok else "per-shard")
            if not fused_ok:
                # raw per-shard bm ops never pass an engine sample
                # site; a fused local_batch_fn group overwrites this
                # (note_engine is last-launch-wins) with the engine
                # that actually ran
                rec.note_engine("host")
        if fused_ok and not self._cluster_active(opt):
            _deadline.check(opt.deadline, "map")
            t_f = _time.perf_counter_ns()
            partials = batch_fn(shards)
            if rec is not None:
                rec.note_stage("map.fused", _time.perf_counter_ns() - t_f)
        else:
            def map_fn(shard):
                return shard, self._bitmap_words_shard(idx, call, shard,
                                                        opt.delta)

            partials = self._map_shards(
                map_fn, shards, idx=idx, call=call, opt=opt,
                adapt=lambda r: list(r.segments.items()),
                local_batch_fn=batch_fn if fused_ok else None,
            )
        for shard, words in partials:
            w = self._np_words(words)
            if w is not None and w.any():
                row.segments[shard] = w

        # Attach row attributes for plain Row() queries (reference
        # executor.go:206 attachment; skipped when excluded).
        if call.name == "Row" and not opt.exclude_row_attrs and not call.has_condition_arg():
            try:
                fname = call.field_arg()
                rowid = call.args.get(fname)
                f = idx.field(fname)
                if f is not None and isinstance(rowid, int):
                    row.attrs = f.row_attrs.attrs(rowid)
            except (ValueError, ExecutionError):
                pass
        return row

    def _bitmap_words_shard(self, idx, call: Call, shard: int,
                            use_delta: bool = True):
        """Evaluate a bitmap call tree for one shard.  Returns packed words
        (device or numpy) or None for empty (reference
        executeBitmapCallShard, executor.go:651).

        ``use_delta`` threads the ?nodelta=1 escape down the per-shard
        recursion (the remote map path and sub-fusion-width shard
        sets): True reads base ⊕ delta through the host overlay, False
        compacts up front and reads pure base."""
        name = call.name
        if name == _EMPTY_CALL:
            return None
        if name == "Row" or name == "Range":
            return self._row_words_shard(idx, call, shard, use_delta)
        if name == "Union":
            out = None
            for child in call.children:
                w = self._bitmap_words_shard(idx, child, shard, use_delta)
                if w is None:
                    continue
                out = w if out is None else bm.b_or(out, w)
            return out
        if name == "Intersect":
            if not call.children:
                raise ExecutionError("Intersect() requires at least one row query")
            out = self._bitmap_words_shard(idx, call.children[0], shard,
                                           use_delta)
            for child in call.children[1:]:
                if out is None:
                    return None
                w = self._bitmap_words_shard(idx, child, shard, use_delta)
                if w is None:
                    return None
                out = bm.b_and(out, w)
            return out
        if name == "Difference":
            if not call.children:
                raise ExecutionError("Difference() requires at least one row query")
            out = self._bitmap_words_shard(idx, call.children[0], shard,
                                           use_delta)
            for child in call.children[1:]:
                if out is None:
                    return None
                w = self._bitmap_words_shard(idx, child, shard, use_delta)
                if w is not None:
                    out = bm.b_andnot(out, w)
            return out
        if name == "Xor":
            out = None
            for child in call.children:
                w = self._bitmap_words_shard(idx, child, shard, use_delta)
                if w is None:
                    continue
                out = w if out is None else bm.b_xor(out, w)
            return out
        if name == "Not":
            if len(call.children) != 1:
                raise ExecutionError("Not() requires a single row query")
            ef = idx.existence_field()
            if ef is None:
                raise ExecutionError(
                    "Not() queries require the index to have 'trackExistence' enabled"
                )
            exist = self._field_row_words(ef, 0, shard, use_delta)
            if exist is None:
                return None
            child = self._bitmap_words_shard(idx, call.children[0], shard,
                                             use_delta)
            if child is None:
                return exist
            return bm.b_not(child, exist)
        if name == "Shift":
            if len(call.children) != 1:
                raise ExecutionError("Shift() requires a single row query")
            n = call.int_arg("n")
            n = 1 if n is None else n
            child = self._bitmap_words_shard(idx, call.children[0], shard,
                                             use_delta)
            if child is None:
                return None
            return bm.b_shift(child, n)
        if name == "Distinct":
            raise ExecutionError("Distinct() is not supported")
        raise ExecutionError(f"unknown call: {name}")

    def _field_row_words(self, f, row_id: int, shard: int,
                         use_delta: bool = True):
        view = f.view(VIEW_STANDARD)
        if view is None:
            return None
        frag = view.fragment(shard)
        if frag is None:
            return None
        # pilosa-lint: allow(lock-discipline) -- unlocked ref-read gate keeps the no-delta fast path lock-free; a detached plane is immutable, so the post-lock row_touched reads a consistent (worst case: stale flight-record note) snapshot
        d = frag._delta
        if d is not None and not d.empty() and use_delta:
            # pending streaming delta: answer from the effective host
            # words rather than device_row, whose matrix restack would
            # MERGE the plane — per-shard reads must not compact, or
            # sustained ingest turns every read into a generation bump
            # (exactly the churn the delta plane exists to absorb).
            # The resident base matrix stays untouched either way.
            with frag._lock:
                arr, owned = frag._row_words_effective_locked(row_id)
                if arr is None:
                    return None
                words = arr if owned else arr.copy()
            if d.row_touched(row_id):
                rec = _observe.current()
                if rec is not None:
                    rec.note_delta(1)
            return words
        # no pending delta — or ?nodelta=1, where device_row's stack
        # merge IS the requested up-front compaction (pure base read)
        return frag.device_row(row_id)

    def _row_words_shard(self, idx, call: Call, shard: int,
                         use_delta: bool = True):
        """Row() in its three forms: standard, time-range, BSI condition
        (reference executeRowShard, executor.go:1441)."""
        cond = call.condition_arg()
        if cond is not None:
            fname, condition = cond
            f = self._field(idx, fname)
            if condition.op == "><":
                lo, hi = condition.int_slice_value()
                return f.range_between(lo, hi, shard)
            if condition.value is None:
                if condition.op == "!=":  # != null -> not null
                    return f.not_null(shard)
                raise ExecutionError("Row(): EQ null condition is not supported")
            if not isinstance(condition.value, int) or isinstance(condition.value, bool):
                raise ExecutionError("Row(): conditions only support integer values")
            return f.range_op(condition.op, condition.value, shard)

        fname = call.field_arg()
        f = self._field(idx, fname)
        row_id = self._bool_row_id(f, call, fname)
        if row_id is None:
            raise ExecutionError(f"Row(): field {fname!r} requires an integer row")

        from_arg = call.args.get("from")
        to_arg = call.args.get("to")
        if from_arg is None and to_arg is None:
            return self._field_row_words(f, row_id, shard, use_delta)

        if not f.time_quantum:
            raise ExecutionError(f"field {fname!r} does not support time-range queries")
        start = parse_time(from_arg) if from_arg is not None else _dt.datetime(1, 1, 1)
        end = parse_time(to_arg) if to_arg is not None else _dt.datetime(9999, 1, 1)
        start, end = self._clamp_to_views(f, start, end)
        if start >= end:
            return None
        return f.row_time(row_id, shard, start, end)

    @staticmethod
    def _clamp_to_views(f, start, end):
        """Clamp an open-ended time range to the span actually covered by
        existing time views (mirrors minMaxViews clamping in
        executeRowsShard, executor.go); the view-name scan is memoized
        by Field.time_view_times."""
        times = f.time_view_times()
        if not times:
            return start, start  # no time views -> empty
        lo = min(times)
        hi = max(times) + _dt.timedelta(days=366)
        return max(start, lo), min(end, hi)

    # ------------------------------------------------------------- counts

    def _execute_count(self, idx, call: Call, shards, opt: ExecOptions) -> int:
        if len(call.children) != 1:
            raise ExecutionError("Count() requires a single bitmap query")
        shards = self._target_shards(idx, shards, opt)
        child = call.children[0]
        fused_ok = self._fuse_eligible(idx, shards, child)

        def compute_counts_once(group):
            # the whole tree INCLUDING the popcount root as one compiled
            # program (ops.expr) — a single dispatch for the group, with
            # XLA fusing AND+popcount so no intersection stack
            # materializes (the host engine keeps the native pairwise
            # kernel for the same reason); per-shard int32 counts summed
            # in Python ints — a single int32 reduce over the stack
            # could wrap past 2^31 set bits.  Sparse trees route the
            # compressed container engine first (ops/containers.py):
            # same single launch, but only the directory-matched
            # container blocks are ever read
            from pilosa_tpu.ops import containers as _containers
            from pilosa_tpu.ops import expr

            m = self._query_mesh(opt)
            cplan = _containers.plan_fused(self, idx, child,
                                           tuple(group), opt)
            if cplan is not None:
                return cplan.counts(mesh=m)
            shape, leaves = self._fused_expr(idx, child, tuple(group),
                                             use_delta=opt.delta)
            counts = expr.evaluate(shape, leaves, counts=True, mesh=m)
            return [int(c) for c in
                    np.asarray(counts, dtype=np.int64)[:len(group)]]

        def compute_counts(group):
            # device-dispatch resilience: a backend RESOURCE_EXHAUSTED
            # evicts every residency-tracked device cache entry
            # (demoting — host twins survive), shrinks the HBM budget
            # so the tier demotes harder, and retries ONCE — the
            # shared run_with_oom_retry wrapper, applied to every
            # fused dispatch site (Count/Row/TopN/coalescer/mesh)
            return _residency.run_with_oom_retry(
                lambda: compute_counts_once(group))

        def batch_fn(group):
            # the clustered local-group path: per-shard counts for the
            # shards THIS node owns, cached under their own key so
            # every owner (replicas included) warms independently —
            # the remote map path caches on the remote side through
            # the single-node branch below when the sub-query arrives
            g = tuple(group)
            probe = self._rc_probe(idx, "count_shards", g, opt,
                                   tree=child)
            if probe is not None:
                rc, key, gens = probe
                hit, val = rc.get(key, gens, self._rc_wait(opt))
                if hit:
                    self._rc_mark_hit()
                    return list(val)
            vals = compute_counts(group)
            if probe is not None and self._rc_fill_ok(opt):
                rc.put(key, gens, tuple(vals), 16 * len(vals))
            return vals

        rec = _observe.current()
        if rec is not None:
            rec.note_path("fused" if fused_ok else "per-shard")
            if not fused_ok:
                rec.note_engine("host")
        if fused_ok and not self._cluster_active(opt):
            _deadline.check(opt.deadline, "map")
            # result-cache probe BEFORE the coalescer: a hit answers
            # pre-window and never occupies a batch slot
            probe = self._rc_probe(idx, "count", tuple(shards), opt,
                                   tree=child)
            if probe is not None:
                rc, ckey, cgens = probe
                hit, val = rc.get(ckey, cgens, self._rc_wait(opt))
                if hit:
                    self._rc_mark_hit()
                    return val
            if (self.coalescer is not None
                    and self.coalescer.eligible(opt)):
                # the coalescer stamps the record itself (path,
                # batch occupancy, queue-wait vs launch split), drops
                # this entry from the batch if its deadline dies in
                # the window, and fills the cache for every flushed
                # batch member
                return self.coalescer.count(self, idx, child,
                                            tuple(shards),
                                            deadline=opt.deadline,
                                            cache_fill=probe,
                                            use_delta=opt.delta,
                                            mesh=self._query_mesh(opt),
                                            tenant=opt.tenant,
                                            # ?nocontainers disables
                                            # the VM too: it executes
                                            # over compressed pools
                                            use_vm=(opt.vm
                                                    and opt.containers))
            t_f = _time.perf_counter_ns()
            total = sum(compute_counts(shards))
            if rec is not None:
                rec.note_stage("map.fused", _time.perf_counter_ns() - t_f)
            if probe is not None and self._rc_fill_ok(opt):
                rc.put(ckey, cgens, total, 32)
            return total

        def map_fn(shard):
            words = self._bitmap_words_shard(idx, child, shard,
                                             opt.delta)
            if words is None:
                return 0
            return int(bm.popcount(words))

        return sum(
            self._map_shards(
                map_fn, shards, idx=idx, call=call, opt=opt,
                adapt=lambda v: [v],
                local_batch_fn=batch_fn if fused_ok else None,
            )
        )

    # --------------------------------------------------------------- TopN

    def _execute_topn(self, idx, call: Call, shards, opt: ExecOptions) -> list[Pair]:
        """Exact TopN via batched device row scans (replaces the
        reference's approximate rank-cache two-phase protocol,
        executor.go:860-1038 — same results on non-tied data, exact
        counts always)."""
        fname = call.string_arg("_field") or call.args.get("_field")
        if not fname:
            raise ExecutionError("TopN() requires a field argument")
        f = self._field(idx, fname)
        n = call.uint_arg("n") or 0
        ids_arg = call.uint_slice_arg("ids")
        threshold = call.uint_arg("threshold") or 0
        attr_name = call.string_arg("attrName")
        attr_values = call.args.get("attrValues")
        tanimoto = call.uint_arg("tanimotoThreshold") or 0
        if tanimoto > 100:
            raise ExecutionError("Tanimoto Threshold is from 1 to 100 only")
        shards = self._target_shards(idx, shards, opt)
        filter_call = call.children[0] if call.children else None

        # A truncated per-shard cache is only exact when there is nothing
        # to merge with: multi-shard aggregation of per-shard top lists
        # loses rows that rank below the truncation point in one shard
        # but high globally.  Post-count filters likewise require the
        # complete row set.  cache_n=0 demands a complete cache.
        single_shard = len(shards) == 1
        cache_n = n if single_shard and not (ids_arg or attr_name or threshold) else 0

        def map_fn(shard):
            view = f.view(VIEW_STANDARD)
            frag = view.fragment(shard) if view is not None else None
            if frag is None:
                return {}
            if filter_call is None:
                cached = frag.cached_row_counts(cache_n)
                if cached is not None:
                    return cached
            gen, row_ids, matrix = frag.device_matrix_with_gen()
            if len(row_ids) == 0:
                return {}
            if filter_call is not None:
                fw = self._bitmap_words_shard(idx, filter_call, shard,
                                              opt.delta)
                if fw is None:
                    return {}
                # Pallas single-pass kernel on TPU for large matrices,
                # fused jnp otherwise (identical counts)
                from pilosa_tpu.ops import pallas_kernels as pk

                counts = pk.row_counts_masked(matrix, fw)
            else:
                counts = bm.row_counts(matrix)
            counts = np.asarray(counts)
            out = {int(r): int(c) for r, c in zip(row_ids, counts) if c > 0}
            if filter_call is None:
                frag.cache_row_counts(out, gen=gen)
            return out

        # Remote sub-queries must return complete per-node counts: n and
        # threshold truncate on *summed* counts, which only the
        # originating reduce can compute (the reference's two-phase
        # candidate protocol, executor.go:860-928, exists for the same
        # reason).
        remote_call = call.clone()
        remote_call.args.pop("n", None)
        remote_call.args.pop("threshold", None)
        remote_call.args.pop("tanimotoThreshold", None)

        fused_ok = self._fuse_eligible(idx, shards, filter_call)

        def batch_fn(group):
            # same hook shape as the Count/Row fused paths: one stacked
            # dispatch for the whole locally-owned group
            return [self._fused_topn_counts(idx, f, filter_call,
                                            tuple(group), opt=opt)]

        if fused_ok and not self._cluster_active(opt):
            _deadline.check(opt.deadline, "map")
            parts = batch_fn(shards)
        else:
            parts = self._map_shards(
                map_fn, shards, idx=idx, call=call, opt=opt,
                adapt=lambda pairs: [{p.id: p.count for p in pairs}],
                remote_call=remote_call,
                local_batch_fn=batch_fn if fused_ok else None,
            )
        totals = {}
        for part in parts:
            for r, c in part.items():
                totals[r] = totals.get(r, 0) + c

        if ids_arg:
            allowed = set(ids_arg)
            totals = {r: c for r, c in totals.items() if r in allowed}
        if attr_name:
            if not isinstance(attr_values, list):
                raise ExecutionError("TopN() attrValues must be a list")
            allowed_vals = set(attr_values)
            row_attrs = f.row_attrs.attrs_bulk(totals)
            totals = {
                r: c
                for r, c in totals.items()
                if row_attrs.get(r, {}).get(attr_name) in allowed_vals
            }
        if tanimoto and filter_call is not None:
            # Tanimoto similarity (reference fragment.top): the count
            # pre-window — full row count strictly inside
            # (|src|*T/100, |src|*100/T), fragment.go:1588-1617 — then
            # the exact coefficient ceil(100*|A∩src| /
            # (|A|+|src|-|A∩src|)) > T, fragment.go:1649-1652.  The
            # reference applies both per shard with per-shard counts;
            # here counts are global — consistent with this executor's
            # exact (non-rank-cache) TopN.
            import math

            src_count = self._execute_count(
                idx, Call("Count", children=[filter_call]), shards, opt)
            if fused_ok and not self._cluster_active(opt):
                # reuse the stacked scan directly — the filtered totals
                # above already warmed the matrix stack, so the
                # unfiltered pass is one more dispatch (and fragment
                # caches make repeats free); no Pair-sort detour
                full_counts = self._fused_topn_counts(idx, f, None,
                                                      tuple(shards),
                                                      opt=opt)
            else:
                full = self._execute_topn(
                    idx, Call("TopN", {"_field": fname}), shards, opt)
                full_counts = {p.id: p.count for p in full}
            lo = src_count * tanimoto / 100.0
            hi = src_count * 100.0 / tanimoto
            kept = {}
            for r, inter in totals.items():
                cnt = full_counts.get(r, 0)
                if not (lo < cnt < hi) or inter == 0:
                    continue
                coeff = math.ceil(inter * 100.0
                                  / (cnt + src_count - inter))
                if coeff > tanimoto:
                    kept[r] = inter
            totals = kept
        elif threshold:
            totals = {r: c for r, c in totals.items() if c >= threshold}

        pairs = sort_pairs([Pair(id=r, count=c) for r, c in totals.items()])
        if n:
            pairs = pairs[:n]
        return pairs

    def _fused_topn_counts(self, idx, f, filter_call,
                           shards: tuple[int, ...],
                           opt: ExecOptions | None = None
                           ) -> dict[int, int]:
        """All shards' TopN row counts, answered from the result cache
        when the scan (field matrix + filter leaves) is still at the
        stamped generations, else in ONE device dispatch — the per-
        fragment TopNCache generalized to the whole cross-shard scan."""
        probe = self._rc_probe(idx, "topn", shards, opt,
                               tree=filter_call, extra=f.name,
                               gen_fields=((f, VIEW_STANDARD),))
        if probe is not None:
            rc, key, gens = probe
            hit, val = rc.get(key, gens, self._rc_wait(opt))
            if hit:
                self._rc_mark_hit()
                return dict(val)
        totals = self._fused_topn_counts_uncached(idx, f, filter_call,
                                                  shards, opt=opt)
        if probe is not None and self._rc_fill_ok(opt):
            rc.put(key, gens, dict(totals),
                   resultcache.result_nbytes(totals))
        return totals

    def _fused_topn_counts_uncached(self, idx, f, filter_call,
                                    shards: tuple[int, ...],
                                    opt: ExecOptions | None = None
                                    ) -> dict[int, int]:
        """All shards' TopN row counts in ONE device dispatch over the
        field's concatenated matrix stack (vs one scan per fragment).
        Unfiltered results also warm every fragment's TopN cache, so
        repeat queries skip the device entirely."""
        view = f.view(VIEW_STANDARD)
        totals: dict[int, int] = {}
        if view is None:
            return totals
        if filter_call is None:
            # whole-scan short-circuit: every fragment's cache complete
            cached_parts = []
            for s in shards:
                frag = view.fragment(s)
                if frag is None:
                    continue
                c = frag.cached_row_counts(0)
                if c is None:
                    cached_parts = None
                    break
                cached_parts.append(c)
            if cached_parts is not None:
                for part in cached_parts:
                    for r, c in part.items():
                        totals[r] = totals.get(r, 0) + c
                return totals

        def _scan():
            # the fused TopN matrix scan, under the shared
            # RESOURCE_EXHAUSTED evict-and-retry.  The matrix stack is
            # fetched INSIDE the retry scope: on an OOM, evict_all()
            # drops its cache entry, so the retry restages the query's
            # own largest operand post-eviction instead of
            # re-dispatching against the pinned pre-OOM buffers.
            stack = f.device_matrix_stack(shards)
            mat_dev, pos_dev = stack[4], stack[3]
            if mat_dev is None:
                return stack, None
            if filter_call is not None:
                filt = self._fused_eval(
                    idx, filter_call, shards,
                    use_delta=opt is None or opt.delta,
                    mesh=self._query_mesh(opt))
                return stack, bm.row_counts_gathered(mat_dev, filt,
                                                     pos_dev)
            return stack, bm.row_counts(mat_dev)

        (gens, row_ids, shard_pos, _pos_dev, _mat_dev), counts = \
            _residency.run_with_oom_retry(_scan)
        if counts is None:
            return totals
        n_rows = len(row_ids)
        counts = np.asarray(counts, dtype=np.int64)[:n_rows]
        if filter_call is not None:
            for rid, c in zip(row_ids, counts):
                if c > 0:
                    rid = int(rid)
                    totals[rid] = totals.get(rid, 0) + int(c)
            return totals

        per_shard: dict[int, dict[int, int]] = {}
        for rid, pos, c in zip(row_ids, shard_pos, counts):
            if c > 0:
                rid, c = int(rid), int(c)
                totals[rid] = totals.get(rid, 0) + c
                per_shard.setdefault(int(pos), {})[rid] = c
        # warm every fragment's cache — including ones whose rows all
        # counted zero, whose complete answer is "no rows".  gens slots
        # are (uid, gen) tokens (field._frag_gen): stamp the cache with
        # the bare gen, and only when the token's uid still matches the
        # live object — a fragment replaced mid-query (resize re-fetch)
        # must not have a fresh object's cache validated by a stale scan
        for pos, s in enumerate(shards):
            frag = view.fragment(s)
            tok = gens[pos]
            if (frag is not None and isinstance(tok, tuple)
                    and tok[0] == frag._uid):
                frag.cache_row_counts(per_shard.get(pos, {}), gen=tok[1])
        return totals

    # --------------------------------------------------------------- Rows

    def _execute_rows(self, idx, call: Call, shards, opt: ExecOptions) -> list[int]:
        # "field=" is the reference's backwards-compat spelling of the
        # positional field (executor.go:1090-1093)
        fname = call.args.get("_field") or call.args.get("field")
        if not fname:
            raise ExecutionError("Rows() requires a field argument")
        f = self._field(idx, fname)
        limit = call.uint_arg("limit")
        previous = call.uint_arg("previous")
        column = call.uint_arg("column")
        shards = self._target_shards(idx, shards, opt)

        # Time fields with from=/to= (or no standard view) scan the
        # covering time views instead of standard — the reference's
        # executeRowsShard view selection with open ends clamped to
        # the existing views' min/max (executor.go:1319-1400); a
        # non-time field ignores from/to exactly as the reference does
        views = [VIEW_STANDARD]
        if f.time_quantum and ("from" in call.args
                                    or "to" in call.args
                                    or f.options.no_standard_view):
            cover = self._time_range_views(f, call)
            if cover is None:
                raise ExecutionError("Rows(): malformed from/to time")
            views = cover
            if not views:
                return []

        def push_down(ids: list[int]) -> list[int]:
            # previous/limit apply inside the shard scan (reference
            # executeRowsShard pushes the filter into the row iterator,
            # executor.go:1040-1071): a shard never ships more than
            # ``limit`` ids past ``previous``, so the host-side merge is
            # bounded by shards*limit, not total row cardinality
            if previous is not None:
                ids = ids[bisect.bisect_right(ids, previous):]
            if limit is not None:
                ids = ids[:limit]
            return ids

        def map_fn(shard):
            if column is not None and shard != column // SHARD_WIDTH:
                return []
            frags = []
            for vname in views:
                view = f.view(vname)
                frag = view.fragment(shard) if view is not None else None
                if frag is not None:
                    frags.append(frag)
            if not frags:
                return []
            if column is not None:
                # one vectorized read of the column's word down the row
                # matrix per view (reference rowFilter ColumnFilter,
                # fragment.go:2618) — a row qualifies when the bit is
                # set in ANY covering view (merged-row semantics)
                off = column % SHARD_WIDTH
                w, b = off // bm.WORD_BITS, off % bm.WORD_BITS
                hit: set[int] = set()
                for frag in frags:
                    ids_arr, matrix = frag._stacked()
                    if len(ids_arr) == 0:
                        continue
                    mask = (matrix[:, w] >> np.uint32(b)) & np.uint32(1)
                    hit.update(int(r) for r in ids_arr[mask.astype(bool)])
                return push_down(sorted(hit))
            if len(frags) == 1:
                return push_down(frags[0].row_ids())
            merged: set[int] = set()
            for frag in frags:
                merged.update(frag.row_ids())
            return push_down(sorted(merged))

        parts = self._map_shards(
            map_fn, shards, idx=idx, call=call, opt=opt, adapt=lambda ids: [ids]
        )
        # bounded k-way merge of the per-shard sorted lists (reference
        # mergeRowIDs, executor.go:1062-1071): dedup on the fly and stop
        # at ``limit`` — never a full union across shards
        out: list[int] = []
        for r in heapq.merge(*parts):
            if out and r == out[-1]:
                continue
            out.append(r)
            if limit is not None and len(out) >= limit:
                break
        return out

    # ------------------------------------------------------------ GroupBy

    def _execute_group_by(self, idx, call: Call, shards, opt: ExecOptions) -> list[GroupCount]:
        """Cartesian intersection counts over child Rows queries
        (reference groupByIterator, executor.go:3058), batched on device:
        each level ANDs the running group bitmap against the whole child
        row matrix and prunes empty groups."""
        if not call.children:
            raise ExecutionError("GroupBy() requires at least one Rows query")
        for child in call.children:
            if child.name != "Rows":
                raise ExecutionError("GroupBy() children must be Rows queries")
        limit = call.uint_arg("limit")
        filter_call = call.call_arg("filter")
        shards = self._target_shards(idx, shards, opt)
        # result cache: a GroupBy's value depends on EVERY row of its
        # child fields, so the stamp covers the whole standard view of
        # each child (plus the filter leaves); eligibility is
        # conservative — plain standard-view children only, filter
        # absent or fused-supported — and the truncation args ride the
        # key, so the post-limit result caches directly
        probe = None
        if not self._cluster_active(opt):
            probe = self._groupby_cache_probe(idx, call, filter_call,
                                              tuple(shards), opt)
            if probe is not None:
                rc, ckey, cgens = probe
                hit, val = rc.get(ckey, cgens, self._rc_wait(opt))
                if hit:
                    self._rc_mark_hit()
                    # deep copy: result translation writes row_key onto
                    # the returned objects and must not mutate the
                    # cached value
                    return self._copy_group_counts(val)
        child_fields = []
        child_allowed: list[set | None] = []
        for child in call.children:
            fname = child.args.get("_field") or child.args.get("field")
            if not fname:
                raise ExecutionError("Rows() requires a field argument")
            child_fields.append(self._field(idx, fname))
            # Rows children with limit/column/previous constraints
            # pre-execute CLUSTER-WIDE once at the originating node and
            # restrict the walk (reference executeGroupBy,
            # executor.go:1084-1117 — except the reference lets each
            # remote node recompute its own LOCAL truncation, which can
            # disagree with the global one; here remotes run the
            # unconstrained walk and the origin filters at reduce, so
            # the restriction is globally consistent)
            if (child.uint_arg("limit") is not None
                    or child.uint_arg("column") is not None
                    or child.uint_arg("previous") is not None):
                allowed = self._execute_rows(idx, child, shards, opt)
                if not allowed:
                    return []
                child_allowed.append(set(allowed))
            else:
                child_allowed.append(None)

        # Fused-supported filters evaluate ONCE as a stacked device
        # computation over the shards THIS node will scan (all of them
        # single-node; the locally-owned group when clustered — the
        # same local-group fusion Count/TopN get via local_batch_fn);
        # map_fn slices its shard's row out of the stack instead of
        # re-evaluating the filter tree per shard.
        filt_stack = None
        shard_pos: dict[int, int] = {}
        if (filter_call is not None
                and self._fuse_eligible(idx, shards, filter_call)):
            if self._cluster_active(opt):
                group = sorted(self.cluster.local_shards(idx.name, shards))
            else:
                group = list(shards)
            if len(group) > 1:
                shard_pos = {s: i for i, s in enumerate(group)}
                filt_stack = self._fused_eval(idx, filter_call,
                                              tuple(group),
                                              use_delta=opt.delta,
                                              mesh=self._query_mesh(opt))

        def map_fn(shard):
            import jax.numpy as jnp

            mats = []
            for f, allowed in zip(child_fields, child_allowed):
                view = f.view(VIEW_STANDARD)
                frag = view.fragment(shard) if view is not None else None
                if frag is None:
                    return {}
                row_ids, matrix = frag.device_matrix()
                if allowed is not None and len(row_ids):
                    keep = np.flatnonzero(np.isin(
                        row_ids, np.fromiter(allowed, dtype=np.int64)))
                    row_ids = row_ids[keep]
                    matrix = matrix[keep] if len(keep) else matrix[:0]
                if len(row_ids) == 0:
                    return {}
                mats.append((f.name, row_ids, matrix))
            # Batched cartesian walk: at each level ONE dispatch counts
            # every (group, child-row) pair and one more builds the
            # surviving groups' masks — vs the reference's per-group
            # iterator (groupByIterator, executor.go:3058).  Pair counts
            # are padded to powers of two so XLA compiles O(log) shapes,
            # not one program per group-count.
            prefixes: list[tuple] = [()]
            # masks stays PADDED (power-of-two rows) across levels; the
            # live-group count is len(prefixes).  Padded garbage rows are
            # never read — counts are host-sliced to the live range.
            masks = None  # device [G_padded, words]; None = unconstrained
            host = isinstance(mats[0][2], np.ndarray) if mats else False
            if filt_stack is not None and shard in shard_pos:
                masks = filt_stack[shard_pos[shard]][None, :]
            elif filter_call is not None:
                base = self._bitmap_words_shard(idx, filter_call, shard,
                                                opt.delta)
                if base is None:
                    return {}
                # keep the filter on the same engine as the child
                # matrices: numpy in host mode (so masked_matrix_counts
                # / and_pairs dispatch to the native kernels), jax on
                # device
                masks = (np.asarray(base)[None, :] if host
                         else jnp.asarray(base)[None, :])
            for level, (fname, row_ids, matrix) in enumerate(mats):
                last = level == len(mats) - 1
                if masks is None:
                    cnts = np.asarray(bm.row_counts(matrix))[None, :]
                else:
                    # Pallas single-pass kernel on TPU for large
                    # products, bm dispatch (native host / jit)
                    # otherwise — identical counts
                    from pilosa_tpu.ops import pallas_kernels as pk

                    cnts = np.asarray(
                        pk.masked_matrix_counts(matrix,
                                                masks))[:len(prefixes)]
                nz_g, nz_r = np.nonzero(cnts)
                if len(nz_g) == 0:
                    return {}
                if last:
                    return {
                        prefixes[g] + ((fname, int(row_ids[r])),):
                            int(cnts[g, r])
                        for g, r in zip(nz_g, nz_r)
                    }
                new_prefixes = [
                    prefixes[g] + ((fname, int(row_ids[r])),)
                    for g, r in zip(nz_g, nz_r)
                ]
                p = len(nz_g)
                pp = _next_pow2(p)
                slots = np.zeros(pp, dtype=np.int32)
                slots[:p] = nz_r
                if masks is None:
                    new_masks = (np.take(matrix, slots, axis=0) if host
                                 else jnp.take(matrix, jnp.asarray(slots),
                                               axis=0))
                else:
                    gsel = np.zeros(pp, dtype=np.int32)
                    gsel[:p] = nz_g
                    new_masks = bm.and_pairs(matrix, masks, slots, gsel)
                prefixes, masks = new_prefixes, new_masks
            return {}

        def gc_adapt(gcs):
            return [
                {
                    tuple((fr.field, fr.row_id) for fr in gc.group): gc.count
                    for gc in gcs
                }
            ]

        # Remote nodes run the UNCONSTRAINED walk: child limit/column/
        # previous are stripped (the origin's cluster-wide allowed sets
        # are the single source of truth; group keys outside them drop
        # at reduce), and so are the top-level limit/offset — a remote
        # truncating its OWN sorted groups would lose partial counts
        # for group keys that span nodes.  Counts are unaffected by the
        # stripping: a group's count never depends on which other rows
        # were walked.
        remote_call = call.clone()
        remote_call.args.pop("limit", None)
        remote_call.args.pop("offset", None)
        for child in remote_call.children:
            child.args.pop("limit", None)
            child.args.pop("column", None)
            child.args.pop("previous", None)

        totals: dict[tuple, int] = {}
        parts = self._map_shards(
            map_fn, shards, idx=idx, call=call, opt=opt, adapt=gc_adapt,
            remote_call=remote_call,
        )
        for part in parts:
            for key, c in part.items():
                if any(
                    allowed is not None and key[i][1] not in allowed
                    for i, allowed in enumerate(child_allowed)
                ):
                    continue
                totals[key] = totals.get(key, 0) + c

        out = [
            GroupCount(group=[FieldRow(field=f, row_id=r) for f, r in key], count=c)
            for key, c in sorted(totals.items())
        ]
        # offset before limit (reference executeGroupBy,
        # executor.go:1135-1149)
        offset = call.uint_arg("offset")
        if offset is not None:
            out = out[offset:] if offset < len(out) else out
        if limit is not None:
            out = out[:limit]
        if probe is not None and self._rc_fill_ok(opt):
            rc.put(ckey, cgens, self._copy_group_counts(out),
                   resultcache.result_nbytes(out) * 2)
        return out

    def _groupby_cache_probe(self, idx, call: Call, filter_call,
                             shards: tuple[int, ...],
                             opt: ExecOptions):
        """The GroupBy cache key/stamp, or None when ineligible: every
        child must be a plain standard-view Rows (time-view covers and
        no-standard-view fields change shape under writes in ways the
        per-view stamp would have to chase), the filter absent or a
        fused-supported tree (anything else has no canonical leaf
        signature to stamp)."""
        sig_children = []
        gen_fields = []
        for child in call.children:
            if child.name != "Rows":
                return None
            fname = child.args.get("_field") or child.args.get("field")
            if not fname:
                return None
            f = idx.field(fname)
            if (f is None or f.time_quantum
                    or f.options.no_standard_view
                    or "from" in child.args or "to" in child.args):
                return None
            sig_children.append((fname, child.uint_arg("limit"),
                                 child.uint_arg("column"),
                                 child.uint_arg("previous")))
            gen_fields.append((f, VIEW_STANDARD))
        if filter_call is not None and not self._fused_supported(
                idx, filter_call):
            return None
        extra = (tuple(sig_children), call.uint_arg("limit"),
                 call.uint_arg("offset"))
        return self._rc_probe(idx, "groupby", shards, opt,
                              tree=filter_call, extra=extra,
                              gen_fields=gen_fields)

    @staticmethod
    def _copy_group_counts(res: list) -> list:
        return [replace(gc, group=[replace(fr) for fr in gc.group])
                for gc in res]

    # --------------------------------------------------- BSI aggregates

    def _local_filter_row(self, idx, call: Call, shards, opt: ExecOptions):
        """Evaluate an aggregate's filter child for the shards this node
        will scan itself.  In a cluster the remote nodes re-evaluate the
        filter for their own shards when the forwarded aggregate arrives,
        so computing it cluster-wide at the origin would be wasted work
        (and a redundant distributed round-trip)."""
        if not call.children:
            return None
        if self._cluster_active(opt):
            local = sorted(self.cluster.local_shards(idx.name, shards))
            return self._execute_bitmap_call(
                idx, call.children[0], local, replace(opt, remote=True, shards=local)
            )
        return self._execute_bitmap_call(idx, call.children[0], shards, opt)

    def _execute_aggregate(self, idx, call: Call, shards, opt: ExecOptions) -> ValCount:
        fname = call.string_arg("field") or call.args.get("field")
        if not fname:
            raise ExecutionError(f"{call.name}() requires a field argument")
        f = self._field(idx, fname)
        shards = self._target_shards(idx, shards, opt)

        fused_ok = self._fuse_eligible(
            idx, shards, call.children[0] if call.children else None,
            extra=f.options.type == FieldType.INT)
        if call.name == "Sum":
            def batch_fn(group):
                return [self._fused_sum(idx, f, call, tuple(group),
                                        use_delta=opt.delta,
                                        mesh=self._query_mesh(opt))]
        else:
            def batch_fn(group):
                return [self._fused_extreme(idx, f, call, tuple(group),
                                            use_delta=opt.delta,
                                            mesh=self._query_mesh(opt))]

        if fused_ok and not self._cluster_active(opt):
            _deadline.check(opt.deadline, "map")
            return batch_fn(shards)[0]

        filter_row = self._local_filter_row(idx, call, shards, opt)
        local_batch_fn = batch_fn if fused_ok else None

        if call.name == "Sum":
            def map_fn(shard):
                s, c = f.sum(filter_row, shard)
                return ValCount(s, c)

            out = ValCount()
            for vc in self._map_shards(
                map_fn, shards, idx=idx, call=call, opt=opt,
                adapt=lambda v: [v], local_batch_fn=local_batch_fn,
            ):
                out = out.add(vc)
            return out

        reducer = "smaller" if call.name == "Min" else "larger"

        def map_fn(shard):
            r = f.min(None if filter_row is None else filter_row, shard) if call.name == "Min" else f.max(
                None if filter_row is None else filter_row, shard
            )
            if r is None:
                return ValCount()
            return ValCount(r[0], r[1])

        out = ValCount()
        for vc in self._map_shards(
            map_fn, shards, idx=idx, call=call, opt=opt,
            adapt=lambda v: [v], local_batch_fn=local_batch_fn,
        ):
            out = getattr(out, reducer)(vc)
        return out

    def _fused_sum(self, idx, f, call: Call, shards: tuple[int, ...],
                   use_delta: bool = True, mesh=None) -> ValCount:
        """Sum over all shards in one stacked dispatch: plane counts from
        the [S, planes, W] BSI stack, exact assembly in Python ints
        (reference fragment.sum per shard, fragment.go:1111; here the
        shard loop is the stack's leading axis)."""
        from pilosa_tpu.ops import bsi as bsi_ops

        P = f.device_plane_stack(shards)
        consider = P[:, bsi_ops.EXISTS_PLANE]
        if call.children:
            filt = self._fused_eval(idx, call.children[0], shards,
                                    use_delta=use_delta, mesh=mesh)
            # the filter stack is padded to the same device multiple
            consider = consider & filt
        pos, neg, count = bsi_ops.plane_counts_stacked(P, consider)
        pos = np.asarray(pos, dtype=np.int64).sum(axis=0)
        neg = np.asarray(neg, dtype=np.int64).sum(axis=0)
        total_count = int(np.asarray(count, dtype=np.int64).sum())
        total = sum((1 << i) * (int(p) - int(n))
                    for i, (p, n) in enumerate(zip(pos, neg)))
        return ValCount(total + total_count * f.options.base, total_count)

    def _fused_extreme(self, idx, f, call: Call,
                       shards: tuple[int, ...],
                       use_delta: bool = True, mesh=None) -> ValCount:
        """Min/Max over all shards from one stacked dispatch: the
        vmapped extreme scans produce every per-shard candidate; the
        host applies the sign-branching of fragment.min/max
        (fragment.go:1147/1191) and folds with smaller/larger."""
        from pilosa_tpu.ops import bsi as bsi_ops

        P = f.device_plane_stack(shards)
        consider = P[:, bsi_ops.EXISTS_PLANE]
        if call.children:
            consider = consider & self._fused_eval(
                idx, call.children[0], shards, use_delta=use_delta,
                mesh=mesh)
        is_min = call.name == "Min"
        want = "min" if is_min else "max"
        (signed_cnt, all_cnt, primary_taken, fallback_taken,
         primary_n, fallback_n) = [
            np.asarray(x)
            for x in bsi_ops.extremes_stacked(P, consider, want)]

        reducer = "smaller" if is_min else "larger"
        out = ValCount()
        for s in range(len(shards)):
            if all_cnt[s] == 0:
                continue
            if signed_cnt[s] > 0:
                # Min: a negative exists -> largest negative magnitude;
                # Max: a positive exists -> largest positive magnitude
                v = bsi_ops.assemble_value(primary_taken[s])
                if is_min:
                    v = -v
                c = int(primary_n[s])
            else:
                # fallback: smallest magnitude among what remains
                v = bsi_ops.assemble_value(fallback_taken[s])
                if not is_min:
                    v = -v  # Max of all-negative = closest to zero
                c = int(fallback_n[s])
            out = getattr(out, reducer)(
                ValCount(v + f.options.base, c))
        return out

    def _execute_extreme_row(self, idx, call: Call, shards, opt: ExecOptions) -> Pair:
        """MinRow/MaxRow (reference executeMinRow/executeMaxRow,
        executor.go:3029)."""
        fname = call.string_arg("field") or call.args.get("field")
        if not fname:
            raise ExecutionError(f"{call.name}() requires a field argument")
        f = self._field(idx, fname)
        shards = self._target_shards(idx, shards, opt)
        is_min = call.name == "MinRow"
        filter_call = call.children[0] if call.children else None
        fused_ok = self._fuse_eligible(idx, shards, filter_call)

        def batch_fn(group):
            # ONE stacked dispatch for the whole group (the TopN scan),
            # then a host argmin/argmax over the row totals — replaces
            # the per-row device round-trips of the old walk
            totals = self._fused_topn_counts(idx, f, filter_call,
                                             tuple(group), opt=opt)
            live = [r for r, c in totals.items() if c > 0]
            if not live:
                return [Pair()]
            rid = min(live) if is_min else max(live)
            return [Pair(id=rid, count=totals[rid])]

        if fused_ok and not self._cluster_active(opt):
            parts = batch_fn(shards)
        else:
            # when fused_ok the local group goes through batch_fn, which
            # evaluates the filter itself — map_fn only runs on this
            # node when fusion is off, so the eager evaluation (which
            # must happen OUTSIDE the worker pool: it fans out itself)
            # is skipped entirely in the fused case
            filter_row = (None if fused_ok
                          else self._local_filter_row(idx, call, shards, opt))

            def map_fn(shard):
                view = f.view(VIEW_STANDARD)
                frag = view.fragment(shard) if view is not None else None
                if frag is None:
                    return Pair()
                ids = frag.row_ids()
                if not is_min:
                    ids = list(reversed(ids))
                fw = (None if filter_row is None
                      else filter_row.shard_segment(shard))
                if filter_row is not None and fw is None:
                    return Pair()
                for rid in ids:
                    words = frag.row(rid)
                    if fw is not None:
                        words = words & fw
                    c = int(np.bitwise_count(words).sum())
                    if c > 0:
                        return Pair(id=rid, count=c)
                return Pair()

            parts = self._map_shards(
                map_fn, shards, idx=idx, call=call, opt=opt,
                adapt=lambda p: [p],
                local_batch_fn=batch_fn if fused_ok else None,
            )

        # Reduce: smallest/largest row id wins; counts for the winning row
        # are summed across shards.  (The reference's reduce keeps one
        # arbitrary shard's count on id ties, executor.go MinRow reduceFn —
        # summing is deterministic and reflects the whole row.)
        out = Pair()
        for p in parts:
            if p.count == 0:
                continue
            if out.count == 0:
                out = Pair(id=p.id, count=p.count)
            elif p.id == out.id:
                out.count += p.count
            elif (p.id < out.id) if is_min else (p.id > out.id):
                out = Pair(id=p.id, count=p.count)
        return out

    # -------------------------------------------------------------- writes

    @staticmethod
    def _bool_row_id(f, call: Call, fname: str):
        """Rewrite true/false row literals to row ids 0/1 on bool fields
        (reference callArgTranslation, executor.go:2678)."""
        v = call.args.get(fname)
        if f.options.type == FieldType.BOOL and isinstance(v, bool):
            return int(v)
        if isinstance(v, bool) or not isinstance(v, int) or v < 0:
            return None
        return v

    def _replicate_to_shard_owners(self, idx, call: Call, shard: int, local_fn) -> bool:
        """Run a single-shard write on every owner replica synchronously
        (reference executeSetBitField, executor.go:2137-2168).

        Under the default ``[replication] write-policy = "all"`` a
        replica that cannot be reached fails the write — the reference
        offers the same all-owners guarantee, with anti-entropy as the
        backstop (this path is byte-identical to the pre-hint behavior,
        regression-pinned).  Under ``write-policy = "available"`` the
        write commits on the reachable owners and each missed delivery
        (breaker-open peer skipped without an RPC, transport error,
        shed-exhausted peer) lands in the per-peer hint queue
        (parallel/hints.py) for replay when the peer heals — at least
        one owner must still apply, or the write fails (no durable
        copy would exist anywhere).

        An owner REFUSING as non-owner means a resize just re-homed the
        shard and its view is fresher than ours: wait for the status
        broadcast, re-resolve the owner set, and retry the refused
        deliveries within the PILOSA_TPU_WRITE_RETRY_S budget."""
        from pilosa_tpu.parallel import hints as _hints
        from pilosa_tpu.parallel.cluster import (
            converge_owner_deliveries, refusal_is_unowned)

        available = (_hints.config().write_policy
                     == _hints.WRITE_POLICY_AVAILABLE)
        applied: set[str] = set()
        hinted: set[str] = set()
        changed = False

        def hint_for(n) -> None:
            # marked now, FLUSHED to the store only once the write has
            # committed on some owner — a write that fails outright
            # must not leave hints that would later replay it
            hinted.add(n.id)

        def delivery_pass() -> bool:
            nonlocal changed
            refused = False
            # mid-rebalance a shard has PENDING owners (backfill
            # targets, or demoted ex-owners after cutover) on top of
            # the serving set: they receive every write too
            # (dual-write), and under the default "hint" policy a
            # missed pending delivery is always hinted — the migration
            # must never make writes stricter than steady state.  With
            # no route override installed, pending is empty and this
            # loop is byte-identical to the legacy replica fan-out.
            route = self.cluster.shard_route(idx.name, shard)
            pending_ids = set(route[1]) if route is not None else set()
            dual_hint = True
            if pending_ids:
                from pilosa_tpu.parallel import rebalance as _rebalance
                dual_hint = (_rebalance.config().dual_write_policy
                             == _rebalance.DUAL_WRITE_HINT)
            for n in self.cluster.write_nodes(idx.name, shard):
                if n.id in applied or n.id in hinted:
                    continue
                pending = n.id in pending_ids
                lenient = available or (pending and dual_hint)
                if n.id == self.cluster.local_id:
                    changed |= local_fn()
                    applied.add(n.id)
                    if pending:
                        from pilosa_tpu.parallel import (
                            rebalance as _rebalance)
                        _rebalance.bump("rebalance.dual_writes")
                    continue
                if lenient and self.cluster.breaker_open(n.id):
                    # known-dead peer: hint without paying the RPC
                    # timeout (the breaker's half-open trial re-admits
                    # it; the replay worker drains the backlog)
                    hint_for(n)
                    continue
                try:
                    if _fi.armed:
                        # failpoint: the production replica write
                        # delivery (errors here fail the write like a
                        # dead owner — or hint it, under "available")
                        _fi.hit("replica.write")
                    res = self.cluster.transport.query_node(
                        n, idx.name, str(call), [shard]
                    )
                except Exception as e:  # noqa: BLE001 — the refusal
                    # contract is a STRING over HTTP (ClientError, not
                    # TransportError), a typed error in-process
                    if refusal_is_unowned(e):
                        refused = True
                        continue
                    if lenient and isinstance(e, ShedByPeerError):
                        # shed-exhausted: proof of life (never feeds
                        # the breaker), but the delivery did not land
                        self.cluster.note_peer_success(n.id)
                        hint_for(n)
                        continue
                    if isinstance(e, TransportError):
                        if lenient:
                            self.cluster.note_peer_failure(n.id)
                            hint_for(n)
                            continue
                        raise ExecutionError(
                            f"write replication to node {n.id} "
                            f"failed: {e}")
                    if pending and dual_hint:
                        # the joiner answers 4xx until it applies the
                        # begin broadcast's schema ("index not found")
                        # — a missed PENDING delivery hints, it never
                        # fails the write (the peer is alive: no
                        # breaker feedback)
                        hint_for(n)
                        continue
                    raise
                if available or pending:
                    self.cluster.note_peer_success(n.id)
                changed |= bool(res[0])
                applied.add(n.id)
                if pending:
                    from pilosa_tpu.parallel import (
                        rebalance as _rebalance)
                    _rebalance.bump("rebalance.dual_writes")
            return refused

        def on_timeout() -> None:
            raise ExecutionError(
                f"shard {shard} owners refused the write as "
                "non-owners and the membership view did not "
                "converge; retry")

        converge_owner_deliveries(delivery_pass, on_timeout)
        if available and not applied:
            raise ExecutionError(
                f"no owner of shard {shard} was reachable; the write "
                "has no durable copy (write-policy=available still "
                "requires one live owner)")
        if hinted:
            store = (getattr(self.node, "hints", None)
                     if self.node is not None else None)
            pql = str(call)
            for nid in sorted(hinted):
                if store is not None:
                    store.append(nid, idx.name, pql, shard)
                else:
                    _hints.bump("hint.dropped")
        return changed

    def _check_remote_shards_owned(self, idx, shards) -> None:
        """Receiver-side ownership gate for WHOLE remote sub-queries
        (reads included): refuse any shard this node does not own per
        its current view with the structured ErrClusterDoesNotOwnShard
        marker, so a stale-view origin fails over instead of reading
        an unmaintained ex-owner copy (satellite of the online
        rebalance: nothing refused stale read sub-queries before)."""
        if (self.cluster is None or self.cluster.transport is None
                or len(self.cluster.sorted_nodes()) < 2):
            return
        for s in shards:
            if not self.cluster.owns_shard(self.cluster.local_id,
                                           idx.name, int(s)):
                raise UnownedShardError(int(s))

    def _check_remote_write_owned(self, idx, shard: int,
                                  opt: ExecOptions | None) -> None:
        """Receiver-side ownership gate for replica write deliveries
        (Set/Clear with remote semantics): refuse a shard this node
        does not own per its CURRENT view instead of silently
        absorbing a stale-view origin's write onto an ex-owner
        (reference api.go ErrClusterDoesNotOwnShard; the import
        message types carry the same gate in node.receive_message)."""
        if opt is None or not opt.remote:
            return
        if (self.cluster is None or self.cluster.transport is None
                or len(self.cluster.sorted_nodes()) < 2):
            return
        if not self.cluster.owns_shard(self.cluster.local_id,
                                       idx.name, shard):
            raise UnownedShardError(shard)

    def _note_new_shard(self, idx, f, shard: int) -> None:
        """Record shard existence locally and broadcast it (reference
        CreateShardMessage, view.go:263-305)."""
        if shard in f.available_shards():
            return
        f._note_shard(shard)
        if self.node is not None:
            self.node.note_shard_created(idx.name, f.name, shard)

    def _parse_set(self, idx, call: Call):
        """Fully validate a Set before any state is touched, so a
        rejected Set leaves no phantom column or shard behind — locally
        or broadcast."""
        col = call.uint_arg("_col")
        if col is None:
            raise ExecutionError("Set() column argument required")
        fname = call.field_arg()
        f = self._field(idx, fname)
        if f.options.type == FieldType.INT:
            value = call.int_arg(fname)
            if value is None:
                raise ExecutionError("Set() row argument required")
            timestamp = None
        else:
            value = self._bool_row_id(f, call, fname)
            if value is None:
                raise ExecutionError("Set() row argument required")
            ts = call.args.get("_timestamp")
            timestamp = parse_time(ts) if ts is not None else None
            if timestamp is not None and f.options.type != FieldType.TIME:
                raise ExecutionError(f"field {fname!r} does not accept timestamps")
        return f, col, value, timestamp

    def _apply_set(self, idx, f, col: int, value, timestamp) -> bool:
        ef = idx.existence_field()
        if ef is not None:
            ef.set_bit(0, col)
        if f.options.type == FieldType.INT:
            return f.set_value(col, value)
        return f.set_bit(value, col, timestamp=timestamp)

    def _execute_set(self, idx, call: Call, opt: ExecOptions) -> bool:
        f, col, value, timestamp = self._parse_set(idx, call)
        if self._cluster_active(opt):
            shard = col // SHARD_WIDTH
            self._note_new_shard(idx, f, shard)
            ef = idx.existence_field()
            if ef is not None:
                self._note_new_shard(idx, ef, shard)
            return self._replicate_to_shard_owners(
                idx, call, shard,
                lambda: self._apply_set(idx, f, col, value, timestamp),
            )
        self._check_remote_write_owned(idx, col // SHARD_WIDTH, opt)
        return self._apply_set(idx, f, col, value, timestamp)

    def _execute_set_local(self, idx, call: Call) -> bool:
        f, col, value, timestamp = self._parse_set(idx, call)
        return self._apply_set(idx, f, col, value, timestamp)

    def _execute_clear(self, idx, call: Call, opt: ExecOptions) -> bool:
        col = call.uint_arg("_col")
        if col is None:
            raise ExecutionError("Clear() column argument required")
        if self._cluster_active(opt):
            return self._replicate_to_shard_owners(
                idx, call, col // SHARD_WIDTH,
                lambda: self._execute_clear_local(idx, call),
            )
        self._check_remote_write_owned(idx, col // SHARD_WIDTH, opt)
        return self._execute_clear_local(idx, call)

    def _execute_clear_local(self, idx, call: Call) -> bool:
        col = call.uint_arg("_col")
        fname = call.field_arg()
        f = self._field(idx, fname)
        if f.options.type == FieldType.INT:
            return f.clear_value(col)
        row_id = self._bool_row_id(f, call, fname)
        if row_id is None:
            raise ExecutionError("Clear() row argument required")
        return f.clear_bit(row_id, col)

    def _forward_to_all_nodes(self, idx, call: Call, changed: bool, shards=None) -> bool:
        """Forward a whole-index write to every other node (each applies
        it to its local fragments/stores); used by ClearRow/Store/attrs.
        `shards` carries the caller's shard restriction (None = all)."""
        for n in self.cluster.sorted_nodes():
            if n.id == self.cluster.local_id:
                continue
            try:
                res = self.cluster.transport.query_node(n, idx.name, str(call), shards)
            except TransportError as e:
                raise ExecutionError(f"write forwarding to node {n.id} failed: {e}")
            r = res[0]
            changed |= bool(r) if isinstance(r, bool) else False
        return changed

    def _execute_clear_row(self, idx, call: Call, shards, opt: ExecOptions) -> bool:
        fname = call.field_arg()
        f = self._field(idx, fname)
        if f.options.type not in (FieldType.SET, FieldType.TIME, FieldType.MUTEX, FieldType.BOOL):
            raise ExecutionError(f"ClearRow() is not supported on {f.options.type} fields")
        row_id = call.uint_arg(fname)
        if row_id is None:
            raise ExecutionError("ClearRow() row argument required")
        changed = False
        for view in list(f.views.values()):
            for frag in list(view.fragments.values()):
                changed |= frag.clear_row(row_id)
        # every node clears its own fragments (replicas included)
        if self._cluster_active(opt):
            changed = self._forward_to_all_nodes(idx, call, changed)
        return changed

    def _execute_store(self, idx, call: Call, shards, opt: ExecOptions) -> bool:
        if len(call.children) != 1:
            raise ExecutionError("Store() requires a single row query")
        fname = call.field_arg()
        f = self._field(idx, fname)
        row_id = call.uint_arg(fname)
        if row_id is None:
            raise ExecutionError("Store() row argument required")
        if self._cluster_active(opt):
            # each node stores the row segments for the shards it owns;
            # the child re-evaluates per node restricted to those shards.
            # The caller's shard restriction travels with the forward.
            target = self._target_shards(idx, shards, opt)
            changed = self._store_local(idx, call, f, row_id, target, opt)
            return self._forward_to_all_nodes(idx, call, changed, shards=target)
        return self._store_local(idx, call, f, row_id, shards, opt)

    def _store_local(self, idx, call: Call, f, row_id: int, shards, opt: ExecOptions) -> bool:
        target = self._target_shards(idx, shards, opt)
        if self.cluster is not None and self.cluster.transport is not None:
            # restrict to locally-owned shards; peers handle their own
            local = sorted(self.cluster.local_shards(idx.name, target))
            src = self._execute_bitmap_call(
                idx, call.children[0], local, replace(opt, remote=True, shards=local)
            )
        else:
            src = self._execute_bitmap_call(idx, call.children[0], target, opt)
        changed = False
        view = f.create_view_if_not_exists(VIEW_STANDARD)
        # Shards to touch: those with source bits, plus those where the
        # target row already has bits to clear.  Shards with neither are
        # skipped — no empty fragments or no-op WAL records.
        target_shards = set(src.segments)
        for shard, frag in view.fragments.items():
            if frag.row_count(row_id) > 0:
                target_shards.add(shard)
        for shard in sorted(target_shards):
            words = src.shard_segment(shard)
            if words is None:
                words = np.zeros(bm.n_words(SHARD_WIDTH), dtype=np.uint32)
            frag = view.create_fragment_if_not_exists(shard)
            if frag.set_row(row_id, words):
                changed = True
                if words.any():
                    f._note_shard(shard)
        return changed

    def _execute_set_row_attrs(self, idx, call: Call, opt: ExecOptions):
        fname = call.args.get("_field")
        if not fname:
            raise ExecutionError("SetRowAttrs() requires a field argument")
        f = self._field(idx, fname)
        row_id = call.uint_arg("_row")
        if row_id is None:
            raise ExecutionError("SetRowAttrs() row argument required")
        attrs = {k: v for k, v in call.args.items() if not k.startswith("_")}
        f.row_attrs.set_attrs(row_id, attrs)
        # attrs replicate to every node (reference stores them on all
        # nodes and reconciles with anti-entropy block diffs, attr.go:90)
        if self._cluster_active(opt):
            self._forward_to_all_nodes(idx, call, False)
        return None

    def _execute_set_column_attrs(self, idx, call: Call, opt: ExecOptions):
        col = call.uint_arg("_col")
        if col is None:
            raise ExecutionError("SetColumnAttrs() column argument required")
        attrs = {k: v for k, v in call.args.items() if not k.startswith("_")}
        idx.column_attrs.set_attrs(col, attrs)
        if self._cluster_active(opt):
            self._forward_to_all_nodes(idx, call, False)
        return None

    # ------------------------------------------------------------ options

    def _execute_options(self, idx, call: Call, shards, opt: ExecOptions):
        """Options(call, ...) wrapper (reference executeOptionsCall,
        executor.go:343)."""
        if len(call.children) != 1:
            raise ExecutionError("Options() requires a single child query")
        new_opt = replace(opt)
        for key, value in call.args.items():
            if key == "columnAttrs":
                new_opt.column_attrs = bool(value)
            elif key == "excludeRowAttrs":
                new_opt.exclude_row_attrs = bool(value)
            elif key == "excludeColumns":
                new_opt.exclude_columns = bool(value)
            elif key == "shards":
                if not isinstance(value, list):
                    raise ExecutionError("Options() shards must be a list")
                new_opt.shards = [int(v) for v in value]
            else:
                raise ExecutionError(f"unknown Options() argument: {key!r}")
        res = self._execute_call(idx, call.children[0], shards, new_opt)
        if isinstance(res, Row):
            # serialization directives ride the result so the wire layer
            # honors per-call Options() the same as URL params
            res.exclude_columns = new_opt.exclude_columns
            res.wants_column_attrs = new_opt.column_attrs
        return res

    # ----------------------------------------------------- key translation

    def _translate_call(self, idx, call: Call) -> Call:
        """Rewrite string keys to uint64 ids on a clone of the call tree
        (reference translateCalls, executor.go:2610).  Read-path misses
        become _Empty/_Noop sentinels; write paths create keys."""
        call = call.clone()
        return self._translate_call_rec(idx, call)

    def _translate_col_key(self, idx, call: Call, create: bool) -> bool:
        """Translate a string _col argument in place.  Returns False when
        the key doesn't exist and wasn't created."""
        v = call.args.get("_col")
        if not isinstance(v, str):
            return True
        if not idx.options.keys:
            raise ExecutionError(
                f"index {idx.name!r} does not use string keys (option keys=true)"
            )
        id = self._translate_one(idx, None, v, create)
        if id is None:
            return False
        call.args["_col"] = id
        return True

    def _translate_one(self, idx, field: str | None, key: str, create: bool):
        """Key -> id; creation is single-writer via the coordinator when
        clustered (reference holder.go:690).  All routing decisions live
        in node.translate_keys_cluster — the local path here only covers
        a bare Executor with no cluster node (unit tests)."""
        node = getattr(self, "node", None)
        if node is not None:
            return node.translate_keys_cluster(idx.name, field, [key],
                                               create=create)[0]
        store = (idx.translate_store if field is None
                 else idx.field(field).translate_store)
        return store.translate_key(key, create=create)

    def _ids_to_keys(self, idx, field: str | None, ids) -> list[str | None]:
        """Id -> key for result translation; read-through via the
        cluster node when present (stale replicas tail the primary)."""
        node = getattr(self, "node", None)
        if node is not None:
            return node.translate_ids_cluster(idx.name, field, ids)
        store = (idx.translate_store if field is None
                 else idx.field(field).translate_store)
        return store.translate_ids(list(ids))

    def _translate_row_key(self, idx, call: Call, arg_key: str, create: bool) -> bool:
        """Translate a string row value held under args[arg_key], where
        arg_key names the field.  Returns False on a read-path miss."""
        v = call.args.get(arg_key)
        if not isinstance(v, str):
            return True
        f = idx.field(arg_key)
        if f is None:
            raise ExecutionError(f"field not found: {arg_key}")
        if not f.options.keys:
            raise ExecutionError(
                f"field {arg_key!r} does not use string keys (option keys=true)"
            )
        id = self._translate_one(idx, arg_key, v, create)
        if id is None:
            return False
        call.args[arg_key] = id
        return True

    def _translate_call_rec(self, idx, call: Call) -> Call:
        name = call.name
        if name == "Set":
            self._translate_col_key(idx, call, create=True)
            self._translate_row_key(idx, call, call.field_arg(), create=True)
            return call
        if name == "Clear":
            if not self._translate_col_key(idx, call, create=False):
                return Call(_NOOP_CALL)
            if not self._translate_row_key(idx, call, call.field_arg(), create=False):
                return Call(_NOOP_CALL)
            return call
        if name == "SetColumnAttrs":
            self._translate_col_key(idx, call, create=True)
            return call
        if name == "SetRowAttrs":
            fname = call.args.get("_field")
            v = call.args.get("_row")
            if isinstance(v, str) and fname:
                f = idx.field(fname)
                if f is None:
                    raise ExecutionError(f"field not found: {fname}")
                if not f.options.keys:
                    raise ExecutionError(f"field {fname!r} does not use string keys")
                call.args["_row"] = self._translate_one(
                    idx, fname, v, create=True)
            return call
        if name in ("Store", "ClearRow"):
            created = name == "Store"
            if not self._translate_row_key(idx, call, call.field_arg(), create=created):
                return Call(_NOOP_CALL)
            call.children = [self._translate_call_rec(idx, c) for c in call.children]
            return call
        if name == "Row" or name == "Range":
            if call.has_condition_arg():
                return call
            fname = next(
                (
                    k
                    for k in call.args
                    if not k.startswith("_") and k not in ("from", "to")
                ),
                None,
            )
            if fname is None:
                return call
            if not self._translate_row_key(idx, call, fname, create=False):
                return Call(_EMPTY_CALL)
            return call
        if name == "Rows":
            fname = call.args.get("_field") or call.args.get("field")
            prev = call.args.get("previous")
            if isinstance(prev, str) and fname:
                f = idx.field(fname)
                if f is None:
                    raise ExecutionError(f"field not found: {fname}")
                if not f.options.keys:
                    raise ExecutionError(f"field {fname!r} does not use string keys")
                id = self._translate_one(idx, fname, prev, create=False)
                if id is None:
                    raise ExecutionError(f"previous key not found: {prev!r}")
                call.args["previous"] = id
            col = call.args.get("column")
            if isinstance(col, str):
                if not idx.options.keys:
                    raise ExecutionError(
                        f"index {idx.name!r} does not use string keys"
                    )
                id = self._translate_one(idx, None, col, create=False)
                if id is None:
                    return Call(_EMPTY_ROWS_CALL)  # unknown column: no rows
                call.args["column"] = id
            return call
        # Pure structural calls: recurse into children and the GroupBy
        # filter argument.
        call.children = [self._translate_call_rec(idx, c) for c in call.children]
        filt = call.args.get("filter")
        if isinstance(filt, Call):
            call.args["filter"] = self._translate_call_rec(idx, filt)
        return call

    def _translate_result(self, idx, call: Call, res):
        """Translate ids back to keys in results (reference
        translateResults, executor.go:2781)."""
        if isinstance(res, Row):
            if idx.options.keys:
                keys = self._ids_to_keys(idx, None, res.columns())
                res.keys = [k or "" for k in keys]
            return res
        if isinstance(res, Pair) or (
            isinstance(res, list) and res and isinstance(res[0], Pair)
        ):
            fname = call.args.get("_field") or call.args.get("field")
            f = idx.field(fname) if fname else None
            if f is not None and f.options.keys:
                pairs = [res] if isinstance(res, Pair) else res
                keys = self._ids_to_keys(idx, f.name,
                                         [p.id for p in pairs])
                for p, k in zip(pairs, keys):
                    p.key = k or ""
            return res
        if call.name == "Rows" and isinstance(res, list):
            fname = call.args.get("_field") or call.args.get("field")
            f = idx.field(fname) if fname else None
            if f is not None and f.options.keys:
                return [k or ""
                        for k in self._ids_to_keys(idx, f.name, res)]
            return res
        if call.name == "GroupBy" and isinstance(res, list):
            # batch per field: one translation call (possibly one
            # read-through RPC) per keyed field, not one per group row
            by_field: dict[str, set[int]] = {}
            for gc in res:
                for fr in gc.group:
                    f = idx.field(fr.field)
                    if f is not None and f.options.keys:
                        by_field.setdefault(f.name, set()).add(fr.row_id)
            keymaps = {
                fname: dict(zip(sorted(ids),
                                self._ids_to_keys(idx, fname, sorted(ids))))
                for fname, ids in by_field.items()
            }
            for gc in res:
                for fr in gc.group:
                    if fr.field in keymaps:
                        fr.row_key = keymaps[fr.field].get(fr.row_id) or ""
            return res
        return res
