"""Query result types (reference pilosa.go / executor.go result structs)."""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class ValCount:
    """BSI aggregate result (reference ValCount, executor.go:3000-3027)."""

    val: int = 0
    count: int = 0

    def add(self, other: "ValCount") -> "ValCount":
        return ValCount(self.val + other.val, self.count + other.count)

    def smaller(self, other: "ValCount") -> "ValCount":
        """Keep the smaller value; merge counts on ties."""
        if other.count == 0:
            return self
        if self.count == 0 or other.val < self.val:
            return other
        if other.val == self.val:
            return ValCount(self.val, self.count + other.count)
        return self

    def larger(self, other: "ValCount") -> "ValCount":
        if other.count == 0:
            return self
        if self.count == 0 or other.val > self.val:
            return other
        if other.val == self.val:
            return ValCount(self.val, self.count + other.count)
        return self


@dataclass
class Pair:
    """(row id/key, count) — TopN and MinRow/MaxRow results
    (reference Pair, pilosa.go)."""

    id: int = 0
    key: str = ""
    count: int = 0


@dataclass
class PairField:
    """Pair tagged with its field (wire form for TopN results)."""

    pair: Pair
    field: str = ""


@dataclass
class FieldRow:
    """One (field, row) coordinate of a GroupBy group
    (reference FieldRow, executor.go:3035)."""

    field: str
    row_id: int = 0
    row_key: str = ""
    value: int | None = None

    def __hash__(self):
        return hash((self.field, self.row_id, self.row_key, self.value))


@dataclass
class GroupCount:
    """One GroupBy result group (reference GroupCount, executor.go:3046)."""

    group: list[FieldRow] = field(default_factory=list)
    count: int = 0


def sort_pairs(pairs: list[Pair]) -> list[Pair]:
    """Count-descending order; ties broken by ascending id for
    determinism.  (The reference sorts by count only, cache.go:324-328,
    leaving tie order unstable — we pin it for reproducibility.)"""
    return sorted(pairs, key=lambda p: (-p.count, p.id, p.key))
