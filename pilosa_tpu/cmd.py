"""CLI: the operational command surface.

Parity target: the reference's cobra command tree (cmd/root.go:28) and
ctl/ implementations — ``server`` (ctl/server.go), ``import``
(ctl/import.go:34-350: CSV buffering, shard grouping, key-aware),
``export`` (ctl/export.go), ``check`` (ctl/check.go: offline file
integrity), ``inspect`` (ctl/inspect.go: fragment dump),
``generate-config``/``config`` (ctl/generate_config.go, ctl/config.go).

Run as ``python -m pilosa_tpu <command>``."""

from __future__ import annotations

import argparse
import csv
import datetime as dt
import signal
import sys
import threading

from pilosa_tpu.config import Config


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="pilosa-tpu",
        description="TPU-native distributed bitmap index")
    sub = p.add_subparsers(dest="command", required=True)

    ps = sub.add_parser("server", help="run a node")
    ps.add_argument("-c", "--config", help="TOML config file")
    ps.add_argument("-d", "--data-dir")
    ps.add_argument("-b", "--bind")
    ps.add_argument("--name")
    ps.add_argument("--seeds", help="comma-separated seed URIs")
    ps.add_argument("--replicas", type=int)
    ps.add_argument("--anti-entropy-interval", type=float)
    ps.add_argument("--heartbeat-interval", type=float)
    ps.add_argument("--long-query-time", type=float,
                    help="seconds; log queries slower than this with "
                         "their profile breakdown ([observe] "
                         "long-query-time; 0 disables)")
    ps.add_argument("--no-admission", action="store_true",
                    help="disable the admission gate ([admission] "
                         "enabled=false): no per-class caps, no load "
                         "shedding, no accept-side thread cap")
    ps.add_argument("--admission-default-deadline", type=float,
                    help="seconds applied to requests without an "
                         "X-Pilosa-Deadline header ([admission] "
                         "default-deadline; 0 = none)")
    for _cls in ("query", "ingest", "internal"):
        ps.add_argument(f"--admission-{_cls}-cap", type=int,
                        help=f"concurrent {_cls}-class requests "
                             f"([admission] {_cls}-cap)")
        ps.add_argument(f"--admission-{_cls}-queue", type=int,
                        help=f"queued {_cls}-class requests beyond the "
                             f"cap; overflow sheds 429 "
                             f"([admission] {_cls}-queue)")
    ps.add_argument("--no-result-cache", action="store_true",
                    help="disable the generation-stamped query result "
                         "cache ([cache] enabled=false): every read "
                         "re-executes on the device")
    ps.add_argument("--cache-budget-bytes", type=int,
                    help="host-memory budget for cached query results "
                         "([cache] budget-bytes)")
    ps.add_argument("--cache-max-entry-bytes", type=int,
                    help="largest single cacheable result "
                         "([cache] max-entry-bytes)")
    ps.add_argument("--cache-ttl", type=float,
                    help="seconds before a cached result ages out even "
                         "unmutated ([cache] ttl; 0 = generations only)")
    ps.add_argument("--no-ragged", action="store_true",
                    help="disable ragged megabatch execution "
                         "([ragged] enabled=false): the coalescer "
                         "merges only identical-shape queries through "
                         "the fused path (pre-ragged behavior)")
    ps.add_argument("--ragged-max-tape", type=int,
                    help="longest op-tape a query may compile to "
                         "before falling back to the per-shape fused "
                         "path ([ragged] max-tape)")
    ps.add_argument("--ragged-max-leaves", type=int,
                    help="most leaf operand stacks a query may stage "
                         "into a ragged bucket ([ragged] max-leaves)")
    ps.add_argument("--no-containers", action="store_true",
                    help="disable the compressed container-directory "
                         "device layout ([containers] enabled=false): "
                         "every fused read routes the dense "
                         "pre-container path")
    ps.add_argument("--containers-threshold", type=float,
                    help="per-fragment fill-ratio ceiling for "
                         "compressed execution ([containers] "
                         "threshold); rows denser than this stay on "
                         "the dense path")
    ps.add_argument("--no-container-kinds", action="store_true",
                    help="disable per-container kind specialization "
                         "([containers] kinds=false): every container "
                         "stays a dense 2048-word bitmap block")
    ps.add_argument("--containers-array-max", type=int,
                    help="cardinality ceiling for the array container "
                         "kind ([containers] array-max, canonical "
                         "4096); lower values only narrow the device "
                         "pick")
    ps.add_argument("--containers-run-cap", type=int,
                    help="most intervals a run container may carry on "
                         "device ([containers] run-cap); noisier "
                         "containers demote to array/bitmap")
    ps.add_argument("--no-mesh", action="store_true",
                    help="disable mesh-native SPMD execution ([mesh] "
                         "enabled=false): fused dispatches run the "
                         "pre-mesh single-device programs and operand "
                         "stacks place on one device")
    ps.add_argument("--mesh-axis-size", type=int,
                    help="local devices joined to the mesh shard axis "
                         "([mesh] axis-size); 0 = all local devices")
    ps.add_argument("--residency-host-budget-bytes", type=int,
                    help="host-RAM tier budget behind HBM ([residency] "
                         "host-budget-bytes); 0 disables tiering "
                         "(misses rebuild inline, evictions drop)")
    ps.add_argument("--residency-disk-path",
                    help="directory for the optional disk spill tier "
                         "behind host RAM ([residency] disk-path); "
                         "empty disables it")
    ps.add_argument("--residency-promote-workers", type=int,
                    help="async promotion worker threads ([residency] "
                         "promote-workers)")
    ps.add_argument("--residency-promote-wait-ms", type=float,
                    help="bound on a demand miss's promotion wait "
                         "before the host-compute fallback "
                         "([residency] promote-wait-ms)")
    ps.add_argument("--no-prefetch", action="store_true",
                    help="disable the predictive host-tier prefetcher "
                         "([residency] prefetch=false)")
    ps.add_argument("--no-ingest-delta", action="store_true",
                    help="disable streaming-ingest delta planes "
                         "([ingest] delta-enabled=false): every write "
                         "mutates base state and bumps the generation "
                         "(pre-delta semantics)")
    ps.add_argument("--ingest-delta-budget-bytes", type=int,
                    help="process-wide bound on pending delta bytes; "
                         "past it writers flush their own fragment "
                         "inline ([ingest] delta-budget-bytes)")
    ps.add_argument("--ingest-compact-threshold-bits", type=int,
                    help="pending bit positions that trigger a "
                         "fragment's compaction on the next scan "
                         "([ingest] compact-threshold-bits)")
    ps.add_argument("--ingest-compact-interval", type=float,
                    help="compactor scan period in seconds, and the "
                         "age bound for small deltas ([ingest] "
                         "compact-interval)")
    ps.add_argument("--breaker-threshold", type=int,
                    help="consecutive transport failures that open a "
                         "peer's circuit breaker ([cluster] "
                         "breaker-threshold)")
    ps.add_argument("--breaker-cooldown", type=float,
                    help="seconds a breaker stays open before the "
                         "half-open trial ([cluster] breaker-cooldown)")
    ps.add_argument("--hedge-max-fraction", type=float,
                    help="bound on hedged replica reads as a fraction "
                         "of RPC volume ([cluster] hedge-max-fraction; "
                         "0 disables hedging)")
    ps.add_argument("--faultinject-armed",
                    help="failpoint spec armed at open ([faultinject] "
                         "armed; e.g. "
                         "'client.request.send=error(transport)*3')")
    ps.add_argument("--write-policy", choices=("all", "available"),
                    help="replica write policy ([replication] "
                         "write-policy): 'all' fails the write when "
                         "any owner is unreachable (default); "
                         "'available' commits on the reachable owners "
                         "and hints the rest for replay")
    ps.add_argument("--hint-max-bytes", type=int,
                    help="total bytes of queued hinted-handoff writes "
                         "([replication] hint-max-bytes; 0 disables "
                         "the hint queue)")
    ps.add_argument("--rebalance-transfer-budget", type=int,
                    help="concurrent shard backfills during an online "
                         "rebalance ([rebalance] transfer-budget)")
    ps.add_argument("--rebalance-dual-write-policy",
                    choices=("hint", "strict"),
                    help="delivery contract for pending shard owners "
                         "during a migration ([rebalance] "
                         "dual-write-policy): 'hint' never fails the "
                         "write over a missed pending copy (queues a "
                         "hint); 'strict' holds pending owners to the "
                         "[replication] write-policy")
    ps.add_argument("--anti-entropy-round-budget", type=float,
                    help="seconds per anti-entropy slice before the "
                         "walk parks its cursor ([anti-entropy] "
                         "round-budget; 0 = whole holder per round)")
    ps.add_argument("--tenants-enabled", action="store_true",
                    help="enable per-tenant isolation ([tenants] "
                         "enabled): weighted-fair admission, "
                         "result-cache soft budgets and residency "
                         "tier quotas per X-Pilosa-Tenant")
    ps.add_argument("--tenant-default-share", type=int,
                    help="concurrency share (per admission class) of "
                         "tenants without their own quota ([tenants] "
                         "default-share)")
    ps.add_argument("--tenant-default-queue", type=int,
                    help="per-class queue depth of tenants without "
                         "their own quota ([tenants] default-queue)")
    ps.add_argument("--tenant-quota", action="append", default=None,
                    metavar="NAME:SHARE[:QUEUE[:CACHE[:RES]]]",
                    help="per-tenant quota entry ([tenants] quotas); "
                         "repeatable — e.g. --tenant-quota "
                         "gold:16:64:0.5 --tenant-quota free:2:8")
    ps.add_argument("--verbose", action="store_true")

    pi = sub.add_parser("import", help="bulk-import CSV bits")
    pi.add_argument("--host", default="http://127.0.0.1:10101")
    pi.add_argument("-i", "--index", required=True)
    pi.add_argument("-f", "--field", required=True)
    pi.add_argument("--create", action="store_true",
                    help="create index/field if missing")
    pi.add_argument("--clear", action="store_true")
    pi.add_argument("--field-type", default="set",
                    choices=["set", "int", "time", "mutex", "bool"])
    pi.add_argument("--min", type=int, default=0)
    pi.add_argument("--max", type=int, default=2**31 - 1)
    pi.add_argument("--time-quantum", default="")
    pi.add_argument("--batch-size", type=int, default=1_000_000,
                    help="bits buffered per request (reference buffers 10M)")
    pi.add_argument("files", nargs="+")

    pe = sub.add_parser("export", help="export a field as CSV")
    pe.add_argument("--host", default="http://127.0.0.1:10101")
    pe.add_argument("-i", "--index", required=True)
    pe.add_argument("-f", "--field", required=True)
    pe.add_argument("-o", "--output", default="-")

    pc = sub.add_parser("check", help="offline integrity check of a data dir")
    pc.add_argument("data_dir")

    pn = sub.add_parser("inspect", help="dump fragment stats from a data dir")
    pn.add_argument("data_dir")
    pn.add_argument("-i", "--index")
    pn.add_argument("-f", "--field")

    sub.add_parser("generate-config", help="print default TOML config")

    pcfg = sub.add_parser("config", help="print effective config")
    pcfg.add_argument("-c", "--config", help="TOML config file")

    args = p.parse_args(argv)
    if args.command in ("server", "import", "check", "inspect"):
        # These touch jax (directly or via bitmap/host_mode device
        # enumeration); on an axon host whose relay died, backend init
        # would hang even pinned to cpu (axon_guard.scrub_axon_backend).
        # Guard AFTER parsing so --help/config/export (pure HTTP) never
        # pay a tunnel probe.
        from pilosa_tpu.axon_guard import guard_dead_relay

        guard_dead_relay()
    return {
        "server": cmd_server,
        "import": cmd_import,
        "export": cmd_export,
        "check": cmd_check,
        "inspect": cmd_inspect,
        "generate-config": cmd_generate_config,
        "config": cmd_config,
    }[args.command](args)


# ---------------------------------------------------------------- server

def cmd_server(args) -> int:
    overrides = {}
    for key in ("data_dir", "bind", "name", "heartbeat_interval"):
        v = getattr(args, key, None)
        if v is not None:  # explicit 0 must override the config file
            overrides[key] = v
    if args.verbose:
        overrides["verbose"] = True
    cfg = Config.load(args.config, overrides=overrides)
    if args.seeds:
        cfg.cluster.seeds = [s for s in args.seeds.split(",") if s]
    if args.replicas is not None:
        cfg.cluster.replicas = args.replicas
    if args.anti_entropy_interval is not None:
        cfg.anti_entropy.interval = args.anti_entropy_interval
    if args.long_query_time is not None:
        cfg.observe.long_query_time = args.long_query_time
    if args.no_admission:
        cfg.admission.enabled = False
    if args.admission_default_deadline is not None:
        cfg.admission.default_deadline = args.admission_default_deadline
    for _cls in ("query", "ingest", "internal"):
        for _kind in ("cap", "queue"):
            v = getattr(args, f"admission_{_cls}_{_kind}", None)
            if v is not None:
                setattr(cfg.admission, f"{_cls}_{_kind}", v)
    if args.no_result_cache:
        cfg.cache.enabled = False
    for key in ("budget_bytes", "max_entry_bytes", "ttl"):
        v = getattr(args, f"cache_{key}", None)
        if v is not None:
            setattr(cfg.cache, key, v)
    if args.no_ragged:
        cfg.ragged.enabled = False
    for key in ("max_tape", "max_leaves"):
        v = getattr(args, f"ragged_{key}", None)
        if v is not None:
            setattr(cfg.ragged, key, v)
    if args.no_containers:
        cfg.containers.enabled = False
    if args.containers_threshold is not None:
        cfg.containers.threshold = args.containers_threshold
    if args.no_container_kinds:
        cfg.containers.kinds = False
    if args.containers_array_max is not None:
        cfg.containers.array_max = args.containers_array_max
    if args.containers_run_cap is not None:
        cfg.containers.run_cap = args.containers_run_cap
    if args.no_mesh:
        cfg.mesh.enabled = "false"
    if args.mesh_axis_size is not None:
        cfg.mesh.axis_size = args.mesh_axis_size
    if args.residency_host_budget_bytes is not None:
        cfg.residency.host_budget_bytes = \
            args.residency_host_budget_bytes
    if args.residency_disk_path is not None:
        cfg.residency.disk_path = args.residency_disk_path
    if args.residency_promote_workers is not None:
        cfg.residency.promote_workers = args.residency_promote_workers
    if args.residency_promote_wait_ms is not None:
        cfg.residency.promote_wait_ms = args.residency_promote_wait_ms
    if args.no_prefetch:
        cfg.residency.prefetch = False
    for key in ("breaker_threshold", "breaker_cooldown",
                "hedge_max_fraction"):
        v = getattr(args, key, None)
        if v is not None:
            setattr(cfg.cluster, key, v)
    if args.faultinject_armed is not None:
        cfg.faultinject.armed = args.faultinject_armed
    if args.write_policy is not None:
        cfg.replication.write_policy = args.write_policy
    if args.hint_max_bytes is not None:
        cfg.replication.hint_max_bytes = args.hint_max_bytes
    if args.anti_entropy_round_budget is not None:
        cfg.anti_entropy.round_budget = args.anti_entropy_round_budget
    if args.rebalance_transfer_budget is not None:
        cfg.rebalance.transfer_budget = args.rebalance_transfer_budget
    if args.rebalance_dual_write_policy is not None:
        cfg.rebalance.dual_write_policy = args.rebalance_dual_write_policy
    if args.no_ingest_delta:
        cfg.ingest.delta_enabled = False
    for key in ("delta_budget_bytes", "compact_threshold_bits",
                "compact_interval"):
        v = getattr(args, f"ingest_{key}", None)
        if v is not None:
            setattr(cfg.ingest, key, v)
    if args.tenants_enabled:
        cfg.tenants.enabled = True
    if args.tenant_default_share is not None:
        cfg.tenants.default_share = args.tenant_default_share
    if args.tenant_default_queue is not None:
        cfg.tenants.default_queue = args.tenant_default_queue
    if args.tenant_quota:
        from pilosa_tpu.serve.tenant import parse_quota_spec

        quotas = dict(cfg.tenants.quotas)
        for spec in args.tenant_quota:
            quotas.update(parse_quota_spec(spec))
        cfg.tenants.quotas = quotas
    return run_server(cfg)


def run_server(cfg: Config, ready_event: threading.Event | None = None,
               stop_event: threading.Event | None = None) -> int:
    """Build and run a node until SIGTERM/SIGINT (reference
    server.Command.Start, server/server.go:137-220)."""
    # Multi-host data plane joins FIRST: jax.distributed must see a
    # fresh runtime, before any import triggers backend init (no-op
    # unless JAX_NUM_PROCESSES/JAX_COORDINATOR_ADDRESS are set).
    from pilosa_tpu.parallel import multihost

    multihost.initialize()

    from pilosa_tpu import stats as _stats
    from pilosa_tpu import tracing as _tracing
    from pilosa_tpu.logger import StandardLogger, VerboseLogger
    from pilosa_tpu.server.server import Server

    log_stream = open(cfg.log_path, "a") if cfg.log_path else None
    log = (VerboseLogger(log_stream) if cfg.verbose
           else StandardLogger(log_stream))
    statsd = None
    if cfg.metric.service == "nop":
        stats = _stats.NOP
    elif cfg.metric.service == "statsd":
        from pilosa_tpu.statsd import StatsdClient

        sd_host, _, sd_port = cfg.metric.host.partition(":")
        statsd = StatsdClient(sd_host or "127.0.0.1",
                              int(sd_port or 8125))
        # fan out so /metrics and /debug/vars keep working too
        stats = _stats.MultiStatsClient([_stats.MemStatsClient(), statsd])
    else:
        stats = _stats.MemStatsClient()
    exporter = None
    if cfg.tracing.endpoint:
        exporter = _tracing.OtlpExporter(cfg.tracing.endpoint,
                                         service=cfg.name or "pilosa-tpu")
        _tracing.set_global_tracer(exporter)
    elif cfg.tracing.enabled:
        _tracing.set_global_tracer(_tracing.MemTracer())
    from pilosa_tpu.runtime import filebudget

    filebudget.set_cap(cfg.max_wal_files)
    srv = Server(
        cfg.expanded_data_dir(),
        host=cfg.host,
        port=cfg.port,
        name=cfg.name or None,
        seeds=cfg.cluster.seeds,
        replica_n=cfg.cluster.replicas,
        partition_n=cfg.cluster.partitions,
        coordinator=cfg.cluster.coordinator,
        anti_entropy_interval=cfg.anti_entropy.interval,
        heartbeat_interval=cfg.heartbeat_interval,
        metric_poll_interval=cfg.metric.poll_interval,
        long_query_time=cfg.cluster.long_query_time,
        max_writes_per_request=cfg.max_writes_per_request,
        tls_cert=cfg.tls.certificate_path or None,
        tls_key=cfg.tls.key_path or None,
        tls_skip_verify=cfg.tls.skip_verify,
        heap_profile=cfg.profile.heap,
        heap_profile_frames=cfg.profile.heap_frames,
        coalescer_enabled=cfg.coalescer.enabled,
        coalescer_window_ms=cfg.coalescer.window_ms,
        coalescer_max_batch=cfg.coalescer.max_batch,
        ragged_enabled=cfg.ragged.enabled,
        ragged_max_tape=cfg.ragged.max_tape,
        ragged_max_leaves=cfg.ragged.max_leaves,
        ragged_prewarm=cfg.ragged.prewarm,
        vm_enabled=cfg.vm.enabled,
        vm_min_domain=cfg.vm.min_domain,
        vm_max_prefetch=cfg.vm.max_prefetch,
        observe_enabled=cfg.observe.enabled,
        observe_recent=cfg.observe.recent,
        observe_long_query_time=cfg.observe.long_query_time,
        observe_device_sample_interval=cfg.observe.device_sample_interval,
        observe_fanin_timeout=cfg.observe.fanin_timeout,
        observe_device_peak_gbps=cfg.observe.device_peak_gbps,
        observe_profiler_max_seconds=cfg.observe.profiler_max_seconds,
        observe_journal=cfg.observe.journal,
        observe_journal_size=cfg.observe.journal_size,
        observe_journal_kinds=cfg.observe.journal_kinds,
        cost_shadow=cfg.cost.shadow,
        admission_enabled=cfg.admission.enabled,
        admission_query_cap=cfg.admission.query_cap,
        admission_query_queue=cfg.admission.query_queue,
        admission_ingest_cap=cfg.admission.ingest_cap,
        admission_ingest_queue=cfg.admission.ingest_queue,
        admission_internal_cap=cfg.admission.internal_cap,
        admission_internal_queue=cfg.admission.internal_queue,
        admission_default_deadline=cfg.admission.default_deadline,
        cache_enabled=cfg.cache.enabled,
        cache_budget_bytes=cfg.cache.budget_bytes,
        cache_max_entry_bytes=cfg.cache.max_entry_bytes,
        cache_ttl=cfg.cache.ttl,
        ingest_delta_enabled=cfg.ingest.delta_enabled,
        containers_enabled=cfg.containers.enabled,
        containers_threshold=cfg.containers.threshold,
        containers_kinds=cfg.containers.kinds,
        containers_array_max=cfg.containers.array_max,
        containers_run_cap=cfg.containers.run_cap,
        mesh_enabled=cfg.mesh.enabled,
        mesh_axis_size=cfg.mesh.axis_size,
        residency_host_budget_bytes=cfg.residency.host_budget_bytes,
        residency_disk_path=cfg.residency.disk_path,
        residency_disk_budget_bytes=cfg.residency.disk_budget_bytes,
        residency_promote_workers=cfg.residency.promote_workers,
        residency_promote_queue=cfg.residency.promote_queue,
        residency_promote_wait_ms=cfg.residency.promote_wait_ms,
        residency_prefetch=cfg.residency.prefetch,
        residency_prefetch_interval=cfg.residency.prefetch_interval,
        ingest_delta_budget_bytes=cfg.ingest.delta_budget_bytes,
        ingest_compact_threshold_bits=cfg.ingest.compact_threshold_bits,
        ingest_compact_interval=cfg.ingest.compact_interval,
        breaker_threshold=cfg.cluster.breaker_threshold,
        breaker_cooldown=cfg.cluster.breaker_cooldown,
        hedge_min_samples=cfg.cluster.hedge_min_samples,
        hedge_deviations=cfg.cluster.hedge_deviations,
        hedge_min_ms=cfg.cluster.hedge_min_ms,
        hedge_max_fraction=cfg.cluster.hedge_max_fraction,
        faultinject_armed=cfg.faultinject.armed,
        write_policy=cfg.replication.write_policy,
        hint_max_bytes=cfg.replication.hint_max_bytes,
        hint_max_age=cfg.replication.hint_max_age,
        hint_replay_interval=cfg.replication.replay_interval,
        anti_entropy_jitter=cfg.anti_entropy.jitter,
        anti_entropy_round_budget=cfg.anti_entropy.round_budget,
        anti_entropy_peer_timeout=cfg.anti_entropy.peer_timeout,
        rebalance_transfer_budget=cfg.rebalance.transfer_budget,
        rebalance_dual_write_policy=cfg.rebalance.dual_write_policy,
        rebalance_cursor_path=cfg.rebalance.cursor_path or None,
        rebalance_backoff_base=cfg.rebalance.backoff_base,
        rebalance_backoff_cap=cfg.rebalance.backoff_cap,
        rebalance_peer_timeout=cfg.rebalance.peer_timeout,
        tenants_enabled=cfg.tenants.enabled,
        tenants_default_share=cfg.tenants.default_share,
        tenants_default_queue=cfg.tenants.default_queue,
        tenants_default_cache_share=cfg.tenants.default_cache_share,
        tenants_default_residency_share=(
            cfg.tenants.default_residency_share),
        tenants_quotas=cfg.tenants.quotas or None,
        logger=log,
        stats=stats,
    )
    if statsd is not None:
        srv._closers.append(statsd.close)
    if exporter is not None:
        # final flush + thread join on shutdown (trailing spans ship)
        srv._closers.append(exporter.close)
    stop = stop_event or threading.Event()

    def _sig(signum, frame):
        stop.set()

    try:
        signal.signal(signal.SIGTERM, _sig)
        signal.signal(signal.SIGINT, _sig)
    except ValueError:
        pass  # not the main thread (tests)
    srv.open()
    log.printf("listening on %s (node %s)", srv.uri, srv.cluster.local_id)
    if ready_event is not None:
        ready_event.set()
    stop.wait()
    srv.close()
    return 0


# ---------------------------------------------------------------- import

# native-path read granularity; tests shrink it to exercise boundaries
_IMPORT_CHUNK_BYTES = 32 << 20


def cmd_import(args) -> int:
    """CSV rows are `row,col[,timestamp]` (set/time/mutex/bool) or
    `col,value` (int) — the reference's two formats (ctl/import.go:278).
    Bits are buffered, then sent via the bulk import API which routes by
    shard server-side."""
    from pilosa_tpu.server.client import InternalClient

    client = InternalClient()
    host = args.host.rstrip("/")
    if args.create:
        opts = {"type": args.field_type}
        if args.field_type == "int":
            opts.update(min=args.min, max=args.max)
        if args.field_type == "time":
            opts.update(timeQuantum=args.time_quantum or "YMDH")
        try:
            client.create_index(host, args.index, {})
        except Exception:
            pass
        try:
            client.create_field(host, args.index, args.field,
                                {"type": args.field_type, **opts})
        except Exception:
            pass

    is_value = args.field_type == "int"
    rows, cols, values, timestamps = [], [], [], []
    n_sent = 0

    def flush():
        nonlocal n_sent, rows, cols, values, timestamps
        if is_value and cols:
            client.import_values(host, args.index, args.field, cols, values)
        elif cols:
            client.import_bits(
                host, args.index, args.field, rows, cols,
                timestamps=[t for t in timestamps] if any(
                    t is not None for t in timestamps) else None,
                clear=args.clear)
        n_sent += len(cols)
        rows, cols, values, timestamps = [], [], [], []

    import contextlib
    from pilosa_tpu import csvload

    def consume_python(stream, path, line_base=0):
        """General path: full CSV semantics incl. timestamps/quoting
        (reference bufferBits, ctl/import.go:173)."""
        reader = csv.reader(stream)
        while True:
            try:
                rec = next(reader)
            except StopIteration:
                return True
            except csv.Error as e:
                print(f"{path}:{line_base + reader.line_num}: "
                      f"bad record: {e}", file=sys.stderr)
                return False
            line_no = line_base + reader.line_num
            if not rec or (len(rec) == 1 and not rec[0].strip()):
                continue
            try:
                if is_value:
                    cols.append(int(rec[0]))
                    values.append(int(rec[1]))
                else:
                    rows.append(int(rec[0]))
                    cols.append(int(rec[1]))
                    timestamps.append(
                        _csv_ts(rec[2]) if len(rec) > 2 and rec[2]
                        else None)
            except (ValueError, IndexError) as e:
                print(f"{path}:{line_no}: bad record {rec!r}: {e}",
                      file=sys.stderr)
                return False
            if len(cols) >= args.batch_size:
                flush()

    def consume_native(stream, path) -> bool:
        """Fast path: the C++ loader parses all-integer two-column
        chunks straight into int64 buffers.  The FIRST chunk it cannot
        own outright — quotes anywhere (a quoted record may span chunk
        boundaries), a chunk with no newline (pathological line
        lengths, lone-CR files), or any record the parser declines —
        permanently hands the rest of the stream to the streaming
        Python path, which alone decides what is an error.  A file
        therefore parses identically with or without the native
        library."""
        raw = csvload.raw_stream(stream)
        line_base = 0
        tail = b""
        while True:
            chunk = csvload.read_chunk(raw, _IMPORT_CHUNK_BYTES)
            buf = tail + chunk
            if not buf:
                return True
            if chunk:
                cut = buf.rfind(b"\n")
                if b'"' in buf or cut < 0:
                    return consume_python(csvload.chain_text(buf, raw),
                                          path, line_base)
                complete, tail = buf[:cut + 1], buf[cut + 1:]
            else:
                complete, tail = buf, b""  # final partial record
            try:
                a, b = csvload.parse_pairs(complete)
            except csvload.NeedsFallback:
                # (complete, tail) is a split of buf — hand back the
                # original buffer, no re-concatenation
                return consume_python(csvload.chain_text(buf, raw),
                                      path, line_base)
            # top up to the batch size exactly — one POST must never
            # exceed it, even with records already buffered
            i = 0
            while i < len(a):
                take = max(1, args.batch_size - len(cols))
                sa = a[i:i + take].tolist()
                sb = b[i:i + take].tolist()
                if is_value:
                    cols.extend(sa)
                    values.extend(sb)
                else:
                    rows.extend(sa)
                    cols.extend(sb)
                    timestamps.extend([None] * len(sa))
                i += take
                if len(cols) >= args.batch_size:
                    flush()
            line_base += complete.count(b"\n")
            if not chunk:
                return True

    for path in args.files:
        stream = sys.stdin if path == "-" else open(path)
        # never close stdin — callers (and later "-" args) still need it
        ctx = contextlib.nullcontext(stream) if path == "-" else stream
        with ctx:
            ok = (consume_native(stream, path) if csvload.available()
                  else consume_python(stream, path))
            if not ok:
                return 1
    flush()
    print(f"imported {n_sent} records into "
          f"{args.index}/{args.field}", file=sys.stderr)
    return 0


def _csv_ts(raw: str) -> str:
    # reference import format uses RFC3339 (ctl/import.go:300)
    return dt.datetime.fromisoformat(raw.replace("Z", "")).isoformat()


# ---------------------------------------------------------------- export

def cmd_export(args) -> int:
    import urllib.request

    host = args.host.rstrip("/")
    out = sys.stdout if args.output == "-" else open(args.output, "w")
    with urllib.request.urlopen(f"{host}/internal/shards/max",
                                timeout=30) as resp:
        import json

        max_shards = json.loads(resp.read())["standard"]
    max_shard = max_shards.get(args.index, 0)
    try:
        for shard in range(max_shard + 1):
            with urllib.request.urlopen(
                    f"{host}/export?index={args.index}&field={args.field}"
                    f"&shard={shard}", timeout=120) as resp:
                out.write(resp.read().decode())
    finally:
        if out is not sys.stdout:
            out.close()
    return 0


# ----------------------------------------------------------------- check

def cmd_check(args) -> int:
    """Open every fragment offline and verify snapshot+WAL load, matrix
    consistency, and roaring round-trip (reference ctl/check.go:30)."""
    from pilosa_tpu.storage.roaring import decode as decode_roaring

    bad = 0
    holder = _open_holder_or_report(args.data_dir)
    if holder is None:
        return 1
    try:
        for d in holder.schema():
            idx = holder.index(d["name"])
            for f in idx.all_fields():
                for vname, view in f.views.items():
                    for shard, frag in sorted(view.fragments.items()):
                        label = f"{d['name']}/{f.name}/{vname}/{shard}"
                        try:
                            frag.check()  # structural invariants
                            blob = frag.to_roaring()
                            decode_roaring(blob)
                            for r in frag.row_ids():
                                frag.row_count(r)
                            print(f"ok   {label}")
                        except Exception as e:
                            bad += 1
                            print(f"FAIL {label}: {e}")
    finally:
        holder.close()
    print(f"{'FAILED' if bad else 'passed'}: {bad} corrupt fragment(s)")
    return 1 if bad else 0


# --------------------------------------------------------------- inspect

def _open_holder_or_report(data_dir: str):
    """Open a data dir for the offline tools, reporting (instead of
    tracebacking) when it is corrupt or locked by a live server."""
    from pilosa_tpu.models.holder import Holder

    try:
        return Holder(data_dir)
    except Exception as e:
        print(f"FAIL open {data_dir}: {e}")
        print("FAILED: holder did not open")
        return None


def cmd_inspect(args) -> int:
    holder = _open_holder_or_report(args.data_dir)
    if holder is None:
        return 1
    bad = 0
    try:
        for d in holder.schema():
            if args.index and d["name"] != args.index:
                continue
            idx = holder.index(d["name"])
            for f in idx.all_fields():
                if args.field and f.name != args.field:
                    continue
                for vname, view in sorted(f.views.items()):
                    for shard, frag in sorted(view.fragments.items()):
                        label = f"{d['name']}/{f.name}/{vname}/shard={shard}"
                        try:
                            ids = frag.row_ids()
                            bits = sum(frag.row_count(r) for r in ids)
                            print(f"{label}: rows={len(ids)} bits={bits} "
                                  f"opN={frag._op_n}")
                        except Exception as e:
                            bad += 1
                            print(f"{label}: FAIL {e}")
    finally:
        holder.close()
    return 1 if bad else 0


# ---------------------------------------------------------------- config

def cmd_generate_config(args) -> int:
    print(Config().to_toml(), end="")
    return 0


def cmd_config(args) -> int:
    print(Config.load(getattr(args, "config", None)).to_toml(), end="")
    return 0


if __name__ == "__main__":
    sys.exit(main())
