"""Device-runtime telemetry: compile tracking, transfer metering, and
memory/residency sampling.

The flight recorder (pilosa_tpu.observe) explains where a query spent
its time; this module explains WHY the device made it slow — the three
failure classes that dominate TPU serving stacks and are invisible to
request-path timings alone (the per-kernel/per-shape compile and memory
telemetry Ragged Paged Attention, arxiv 2604.15464, motivates; DrJAX,
arxiv 2403.07128, makes the per-node runtime-visibility case for the
map-reduce fan-out):

- **XLA recompiles** — a query hitting a fresh canonical shape pays a
  trace+lower+compile (tens of ms to seconds) that looks like an
  inexplicable latency spike.  Every ``_jit_*`` kernel in ``ops/`` is
  wrapped by :func:`instrument`, which detects a jit cache miss (via
  the jitted callable's ``_cache_size``, falling back to first-seen
  shape keys on jax versions without it) and times the first-lowering
  call, keyed per (kernel, canonical operand shape).
- **Host→device transfer bursts** — ``ops/bitmap.chunked_device_put``
  (the one staging funnel for fragment matrices, BSI planes, and field
  row stacks) reports bytes/chunks per labeled owner through
  :func:`note_transfer`.
- **Residency churn / HBM pressure** — the process-wide residency
  manager's usage/budget/evictions/high-water plus each device's
  ``memory_stats()`` (bytes_in_use vs bytes_limit, where the backend
  reports them) are sampled on demand and by the optional background
  sampler.

Exposure: ``GET /debug/devices`` (snapshot()), ``device.*`` /
``compile.*`` / ``residency.*`` gauges+histograms in the stats
registry (publish_gauges(), called at /metrics and /debug/vars scrape
time and by the ``[observe] device-sample-interval`` sampler), and
compile attribution stamped onto the active QueryRecord so a slow
query answers "slow because it compiled" in one request.

Lock discipline mirrors observe.py: the per-dispatch fast path is one
attribute read + two C calls (``_cache_size``), no locks; the
observer's lock is touched only on the rare compile/transfer events
and on snapshot.  Budget: < 1% of the coalesced Count path
(bench.py extras.devobs).
"""

from __future__ import annotations

import threading
import time

from pilosa_tpu import observe as _observe


class _CompileStat:
    """Per-(kernel, canonical shape) compile accounting."""

    __slots__ = ("count", "total_ns", "last_ns", "first_unix")

    def __init__(self):
        self.count = 0
        self.total_ns = 0
        self.last_ns = 0
        self.first_unix = time.time()


class DeviceObserver:
    """Process-wide device-runtime registry (one per process, like the
    residency manager — compiles and transfers are process-wide by
    nature: the jit caches and the staging funnel are shared)."""

    def __init__(self):
        self.enabled = True
        # optional stats client (server assembly wires it in) so
        # compile events publish a compile.ms histogram live
        self.stats = None
        self._lock = threading.Lock()
        # kernel -> shape key -> _CompileStat
        self._compiles: dict[str, dict[str, _CompileStat]] = {}
        self.compile_count = 0
        self.compile_ns = 0
        # transfer metering: label -> [bytes, chunks, puts]
        self._transfers: dict[str, list[int]] = {}
        self.transfer_bytes = 0
        self.transfer_chunks = 0
        self.transfer_puts = 0
        # device-OOM recoveries: RESOURCE_EXHAUSTED launches that
        # evicted residency and retried (executor fused Count path)
        self.oom_retries = 0

    # -------------------------------------------------------------- events

    def note_compile(self, kernel: str, shape_key: str, ns: int) -> None:
        """One detected compile (cache-miss first lowering) of
        ``kernel`` at ``shape_key``, costing ``ns`` wall time.  Also
        stamps the query record active on this thread, so the query
        that PAID the compile carries it."""
        with self._lock:
            per_shape = self._compiles.setdefault(kernel, {})
            st = per_shape.get(shape_key)
            if st is None:
                # bound the per-kernel shape table: a pathological
                # shape churn must not grow the registry without limit
                if len(per_shape) >= 256:
                    shape_key = "<overflow>"
                    st = per_shape.get(shape_key)
                if st is None:
                    st = per_shape[shape_key] = _CompileStat()
            st.count += 1
            st.total_ns += ns
            st.last_ns = ns
            self.compile_count += 1
            self.compile_ns += ns
        rec = _observe.current()
        if rec is not None:
            rec.note_compile(kernel, ns)
        stats = self.stats
        if stats is not None:
            try:
                stats.with_tags(f"kernel:{kernel}").histogram(
                    "compile.ms", ns / 1e6)
            except Exception:  # noqa: BLE001 — telemetry never raises
                pass

    def note_oom_retry(self) -> None:
        """One RESOURCE_EXHAUSTED launch recovered by evict-and-retry
        (device.oom_retries)."""
        with self._lock:
            self.oom_retries += 1

    def note_transfer(self, nbytes: int, chunks: int,
                      label: str = "other") -> None:
        """One host→device staging put of ``nbytes`` in ``chunks``
        pieces, attributed to ``label`` (the owning cache)."""
        if not self.enabled:
            return
        with self._lock:
            t = self._transfers.setdefault(label, [0, 0, 0])
            t[0] += nbytes
            t[1] += chunks
            t[2] += 1
            self.transfer_bytes += nbytes
            self.transfer_chunks += chunks
            self.transfer_puts += 1

    # ------------------------------------------------------------- exports

    @staticmethod
    def device_memory() -> list[dict]:
        """Per-device memory stats where the backend reports them (TPU
        does; CPU returns none — the entry still lists the device so
        the operator sees the topology)."""
        out = []
        try:
            import jax

            for d in jax.devices():
                entry: dict = {"id": d.id, "platform": d.platform}
                try:
                    ms = d.memory_stats()
                except Exception:  # noqa: BLE001
                    ms = None
                if ms:
                    entry["bytesInUse"] = ms.get("bytes_in_use")
                    entry["bytesLimit"] = ms.get("bytes_limit")
                    entry["peakBytesInUse"] = ms.get("peak_bytes_in_use")
                out.append(entry)
        except Exception:  # noqa: BLE001 — backend init failure ≠ 500
            pass
        return out

    def snapshot(self) -> dict:
        """The /debug/devices document: per-kernel/per-shape compiles,
        per-label transfers, residency accounting, device memory."""
        from pilosa_tpu.runtime import residency

        with self._lock:
            kernels = {}
            for kernel, per_shape in self._compiles.items():
                shapes = {
                    key: {"compiles": st.count,
                          "totalMs": round(st.total_ns / 1e6, 3),
                          "lastMs": round(st.last_ns / 1e6, 3)}
                    for key, st in per_shape.items()
                }
                kernels[kernel] = {
                    "compiles": sum(s.count for s in per_shape.values()),
                    "totalMs": round(sum(s.total_ns
                                         for s in per_shape.values())
                                     / 1e6, 3),
                    "shapes": shapes,
                }
            transfers = {
                label: {"bytes": b, "chunks": c, "puts": p}
                for label, (b, c, p) in self._transfers.items()
            }
            out = {
                "enabled": self.enabled,
                "compile": {
                    "total": self.compile_count,
                    "totalMs": round(self.compile_ns / 1e6, 3),
                    "programEvictions": _program_evictions(),
                    "kernels": kernels,
                },
                "transfer": {
                    "bytes": self.transfer_bytes,
                    "chunks": self.transfer_chunks,
                    "puts": self.transfer_puts,
                    "byLabel": transfers,
                },
                "oomRetries": self.oom_retries,
            }
        out["residency"] = residency.manager().stats()
        # tiered residency: the promotion pool's live state joins the
        # manager's tier split (/debug/devices answers "is the working
        # set over HBM, and is promotion keeping up" in one read)
        out["residency"]["promoter"] = residency.promoter().stats()
        out["devices"] = self.device_memory()
        return out

    def publish_gauges(self, stats) -> None:
        """Push the device.*/compile.*/residency.* gauge families into
        a stats registry — called at /metrics and /debug/vars scrape
        time (so the surface is never stale) and by the background
        sampler (so statsd-only deployments see them too).  Totals are
        gauges, not counters: they are already cumulative here, and
        re-publishing a cumulative value through a counter would
        double-count."""
        from pilosa_tpu.runtime import residency

        with self._lock:
            stats.gauge("compile.count", self.compile_count)
            stats.gauge("compile.total_ms",
                        round(self.compile_ns / 1e6, 3))
            # fused-program cache pressure (ops/expr._compiled): a
            # nonzero value means live tree shapes outnumber retained
            # programs and evicted shapes silently re-trace on reuse
            stats.gauge("compile.program_evictions",
                        _program_evictions())
            stats.gauge("device.transfer_bytes", self.transfer_bytes)
            stats.gauge("device.transfer_chunks", self.transfer_chunks)
            stats.gauge("device.transfer_puts", self.transfer_puts)
            stats.gauge("device.oom_retries", self.oom_retries)
        r = residency.manager().stats()
        stats.gauge("residency.usage_bytes", r["total"])
        stats.gauge("residency.budget_bytes", r["budget"])
        stats.gauge("residency.entries", r["entries"])
        stats.gauge("residency.evictions", r["evictions"])
        stats.gauge("residency.admits", r.get("admits", 0))
        stats.gauge("residency.high_water_bytes",
                    r.get("high_water", r["total"]))
        kinds = r.get("kinds") or {}
        # the compressed-vs-dense residency split (roaring-on-TPU
        # container pools vs dense plane tensors, ops/containers.py)
        stats.gauge("residency.dense_bytes", kinds.get("dense", 0))
        stats.gauge("residency.compressed_bytes",
                    kinds.get("compressed", 0))
        # tiered residency (runtime/residency.py): the host/disk tier
        # occupancy, demotion/promotion flow, and degradation counters
        # — residency.tier.* + prefetch.* families, published
        # unconditionally (zeros pre-pressure) so the surfaces are
        # scrape-visible before the first over-HBM working set
        t = r.get("tiers") or {}
        host = t.get("host") or {}
        disk = t.get("disk") or {}
        stats.gauge("residency.tier.host_bytes", host.get("bytes", 0))
        stats.gauge("residency.tier.host_budget_bytes",
                    host.get("budget", 0))
        stats.gauge("residency.tier.host_entries",
                    host.get("entries", 0))
        stats.gauge("residency.tier.disk_bytes", disk.get("bytes", 0))
        stats.gauge("residency.tier.disk_entries",
                    disk.get("entries", 0))
        stats.gauge("residency.tier.demotions", t.get("demotions", 0))
        stats.gauge("residency.tier.hits", t.get("hits", 0))
        stats.gauge("residency.tier.misses", t.get("misses", 0))
        stats.gauge("residency.tier.spills", t.get("spills", 0))
        stats.gauge("residency.tier.disk_hits", t.get("diskHits", 0))
        stats.gauge("residency.tier.fallbacks", t.get("fallbacks", 0))
        stats.gauge("residency.tier.oom_budget_shrinks",
                    t.get("oomBudgetShrinks", 0))
        p = residency.promoter().stats()
        stats.gauge("residency.tier.promotions", p.get("promotions", 0))
        stats.gauge("residency.tier.promotion_failures",
                    p.get("failures", 0))
        stats.gauge("residency.tier.promotion_sheds", p.get("sheds", 0))
        stats.gauge("residency.tier.promote_queue", p.get("queue", 0))
        stats.gauge("prefetch.issued", p.get("prefetchIssued", 0))
        stats.gauge("prefetch.completed",
                    p.get("prefetchCompleted", 0))
        stats.gauge("prefetch.shed", p.get("prefetchShed", 0))
        stats.gauge("prefetch.useful", t.get("prefetchUseful", 0))
        stats.gauge("prefetch.enabled",
                    1 if residency.config().prefetch else 0)
        for d in self.device_memory():
            if d.get("bytesInUse") is None:
                continue
            tagged = stats.with_tags(f"device:{d['id']}",
                                     f"platform:{d['platform']}")
            tagged.gauge("device.bytes_in_use", d["bytesInUse"])
            if d.get("bytesLimit") is not None:
                tagged.gauge("device.bytes_limit", d["bytesLimit"])


def _program_evictions() -> int:
    """Evictions from the fused-program lru cache — imported lazily so
    reading device telemetry never forces the ops stack in."""
    import sys

    expr = sys.modules.get("pilosa_tpu.ops.expr")
    if expr is None:
        return 0
    return expr.program_evictions()


_global = DeviceObserver()
_global_lock = threading.Lock()


def observer() -> DeviceObserver:
    """The process-wide observer (compiles/transfers are process-wide,
    like the residency budget)."""
    return _global


def reset() -> DeviceObserver:
    """Replace the global observer (tests)."""
    global _global
    with _global_lock:
        _global = DeviceObserver()
        return _global


def note_transfer(nbytes: int, chunks: int, label: str = "other") -> None:
    _global.note_transfer(nbytes, chunks, label)


# --------------------------------------------------------------- instrument


def _shape_key(args, kwargs) -> str:
    """Canonical-shape key for one call: dtype[dims] per array operand,
    repr for static scalars — the per-kernel axis compile telemetry is
    bucketed on."""
    parts = []
    for a in args:
        shp = getattr(a, "shape", None)
        if shp is not None:
            parts.append(f"{getattr(a, 'dtype', '?')}"
                         f"[{','.join(str(s) for s in shp)}]")
        else:
            parts.append(repr(a))
    for k in sorted(kwargs):
        parts.append(f"{k}={kwargs[k]!r}")
    return "(" + ", ".join(parts) + ")"


class _InstrumentedJit:
    """Wraps one jitted callable with compile-event detection.

    Fast path (cache hit, observer disabled): one attribute read and at
    most two ``_cache_size`` C calls on top of the dispatch — ~0.3 us,
    vs the ~20 us device-dispatch floor the serving path is built
    around (VERDICT round 5), so the <1% budget holds by construction.

    Detection is the jit cache-size delta around the call: jit only
    grows its cache on a genuine trace+lower+compile, so canonical-form
    aliasing (weak types, distinct-but-equal shapes) can never
    double-count the way a homegrown shape table would.  On jax builds
    without ``_cache_size`` the wrapper falls back to first-seen shape
    keys — approximate: the per-wrapper ``_seen`` set outlives
    ``jax.clear_caches``, so a recompile of an already-seen shape goes
    undetected there (the primary cache-size path has no such blind
    spot).  Concurrent first calls may attribute one compile to two
    threads — compile events are rare and the count stays within ±1 of
    truth, which the telemetry (not billing) use tolerates."""

    __slots__ = ("fn", "name", "_seen", "_has_cache_size")

    def __init__(self, name: str, fn):
        self.fn = fn
        self.name = name
        self._seen: set[str] = set()
        self._has_cache_size = hasattr(fn, "_cache_size")

    def __call__(self, *args, **kwargs):
        obs = _global
        if not obs.enabled:
            return self.fn(*args, **kwargs)
        if self._has_cache_size:
            try:
                s0 = self.fn._cache_size()
            except Exception:  # noqa: BLE001
                s0 = -1
            t0 = time.perf_counter_ns()
            out = self.fn(*args, **kwargs)
            if s0 >= 0:
                try:
                    grew = self.fn._cache_size() > s0
                except Exception:  # noqa: BLE001
                    grew = False
                if grew:
                    obs.note_compile(self.name, _shape_key(args, kwargs),
                                     time.perf_counter_ns() - t0)
            return out
        key = _shape_key(args, kwargs)
        if key in self._seen:
            return self.fn(*args, **kwargs)
        t0 = time.perf_counter_ns()
        out = self.fn(*args, **kwargs)
        self._seen.add(key)
        obs.note_compile(self.name, key, time.perf_counter_ns() - t0)
        return out

    def __getattr__(self, item):
        # lower(), clear_cache(), _cache_size etc. reach the jit object
        return getattr(self.fn, item)


def instrument(name: str, fn):
    """Wrap a jitted callable so cache-miss compiles are detected,
    timed, and recorded under ``name`` — the one hook every ``_jit_*``
    kernel (ops/bitmap.py, ops/bsi.py, the fused expression programs,
    the Pallas entry points) routes through."""
    return _InstrumentedJit(name, fn)


# ------------------------------------------------------------------ sampler


class DeviceSampler:
    """Background gauge loop for the device families ([observe]
    device-sample-interval) — the statsd-shipping analog of scrape-time
    publishing (a pull scraper gets fresh gauges at /metrics anyway;
    push backends need the loop)."""

    def __init__(self, stats, interval: float):
        self.stats = stats
        self.interval = interval
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        if self.interval <= 0 or self.stats is None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="device-sampler")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                observer().publish_gauges(self.stats)
            except Exception:  # noqa: BLE001 — never take the loop down
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
