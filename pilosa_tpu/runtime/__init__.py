"""Host runtime services: device-memory residency management."""
