"""Global budget for long-lived append file handles (WAL fds).

The reference transparently caps open files and mmaps process-wide
(syswrap/os.go:41 OpenFile wrapping, syswrap/mmap.go:27): past the
limit, files close behind the scenes and transparently reopen on the
next use, so a 10B-column index (~9.5k fragments, one WAL fd each)
cannot blow ``ulimit -n``.  This module is that wrapper for the one
class of long-lived fd this design holds: fragment WAL appenders.

``BudgetedAppendFile`` looks like an append-only file (write/flush/
close) but its OS fd is owned by the global ``FileBudget`` LRU: when
the number of OPEN fds would exceed the cap, the least-recently-used
handle's fd closes; the next write on that handle transparently
reopens the path with ``"ab"``.  Append position is the file's end, so
an evict/reopen cycle is invisible to the writer.

Locking: every fd state transition (open, evict, close) happens under
the ONE registry lock — never under a caller's lock — so eviction can
never deadlock against a writer (the round-3 membership/snapshot work
taught that two-lock hierarchies across instances always find a way to
invert).  Writes pin their handle (``_busy``) so eviction skips fds
that are mid-write; the write syscall itself runs outside the registry
lock.

Cap configuration: ``PILOSA_TPU_MAX_WAL_FILES`` env (default 512 —
well under the common 1024 ``ulimit -n``, leaving room for sockets,
snapshots, SQLite attr stores, and transient opens), or
``set_cap()`` from server config.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

DEFAULT_CAP = 512


class BudgetedAppendFile:
    """Append-only file whose fd the global budget may close at any
    time between writes; reopens transparently.  One writer at a time
    (fragment WAL appends run under the fragment lock)."""

    __slots__ = ("path", "_budget", "_busy", "_closed")

    def __init__(self, path: str, budget: "FileBudget",
                 truncate: bool = False):
        self.path = path
        self._budget = budget
        self._busy = False
        self._closed = False
        # open eagerly so creation errors surface at the call site
        # (and "wb" truncation happens exactly once, never on reopen)
        budget._acquire(self, truncate=truncate)

    def write(self, data: bytes) -> None:
        f = self._budget._pin(self)
        try:
            f.write(data)
            f.flush()
        finally:
            self._budget._unpin(self)

    def close(self) -> None:
        self._budget._release(self)

    def rename_to(self, new_path: str) -> None:
        """``os.replace(self.path, new_path)`` + retarget, atomic
        against eviction/reopen: a reopen between the rename and the
        retarget would recreate the OLD path and append acked records
        to a file nobody replays (the fragment snapshot's phase-3
        overflow-segment commit needs exactly this)."""
        self._budget._rename(self, new_path)


class FileBudget:
    """Process-wide LRU of open append fds (reference syswrap cap)."""

    def __init__(self, cap: int):
        self._cap = max(1, int(cap))
        self._lock = threading.Lock()
        # handle -> open file object, LRU order (oldest first)
        self._open: "OrderedDict[BudgetedAppendFile, object]" = \
            OrderedDict()
        self.evictions = 0
        self.reopens = 0

    # ------------------------------------------------------------- config

    @property
    def cap(self) -> int:
        return self._cap

    def set_cap(self, cap: int) -> None:
        with self._lock:
            self._cap = max(1, int(cap))
            victims = self._pop_victims()
        for v in victims:
            v.close()

    def open_count(self) -> int:
        with self._lock:
            return len(self._open)

    # ---------------------------------------------------------- lifecycle
    #
    # All open()/close() SYSCALLS run OUTSIDE the registry lock: in
    # over-cap steady state (the 10B shape: ~9.5k fragments vs a 512
    # cap) nearly every append is an LRU miss, and reopen+evict-close
    # under one global mutex would serialize every fragment's write
    # path on fd churn.  Only the OrderedDict bookkeeping is locked.
    # An evicted victim's fd closes after release of the lock — safe:
    # non-busy means no write in flight, every write flushes before
    # unpin, and "ab" reopens position atomically at end-of-file.

    def _acquire(self, h: BudgetedAppendFile, truncate: bool) -> None:
        f = open(h.path, "wb" if truncate else "ab")
        with self._lock:
            self._open[h] = f
            self._open.move_to_end(h)
            victims = self._pop_victims()
        for v in victims:
            v.close()

    def _pin(self, h: BudgetedAppendFile):
        """Return the handle's open file, reopening if evicted, and
        mark it busy so eviction skips it until _unpin."""
        with self._lock:
            if h._closed:
                raise ValueError(f"write to closed {h.path}")
            f = self._open.get(h)
            if f is not None:  # fast path: LRU hit, no syscalls
                self._open.move_to_end(h)
                h._busy = True
                return f
        nf = open(h.path, "ab")
        extra = None
        with self._lock:
            if h._closed:
                extra = nf
            else:
                f = self._open.get(h)
                if f is None:
                    self._open[h] = nf
                    self.reopens += 1
                    f = nf
                else:
                    extra = nf  # racing insert won; drop ours
                self._open.move_to_end(h)
                h._busy = True
            victims = self._pop_victims()
        if extra is not None:
            extra.close()
        for v in victims:
            v.close()
        if h._closed:
            raise ValueError(f"write to closed {h.path}")
        return f

    def _unpin(self, h: BudgetedAppendFile) -> None:
        with self._lock:
            h._busy = False

    def _rename(self, h: BudgetedAppendFile, new_path: str) -> None:
        # the rename syscall MUST sit inside the lock: its whole point
        # is atomicity against a concurrent eviction/reopen (rare —
        # once per snapshot commit, never on the append path)
        with self._lock:
            os.replace(h.path, new_path)
            h.path = new_path

    def _release(self, h: BudgetedAppendFile) -> None:
        with self._lock:
            h._closed = True
            f = self._open.pop(h, None)
        if f is not None:
            f.close()

    def _pop_victims(self) -> list:
        # under self._lock; returns file objects for the caller to
        # close OUTSIDE it.  Busy handles are skipped, so with W
        # concurrent writers the transient fd count is cap + W — the
        # same slack the reference's wrapper allows for in-flight files
        victims = []
        while len(self._open) > self._cap:
            victim = next((k for k in self._open if not k._busy), None)
            if victim is None:
                break  # everything busy: nothing safe to close
            victims.append(self._open.pop(victim))
            self.evictions += 1
        return victims


_budget = FileBudget(int(os.environ.get("PILOSA_TPU_MAX_WAL_FILES",
                                        str(DEFAULT_CAP))))


def budget() -> FileBudget:
    return _budget


def open_append(path: str, truncate: bool = False) -> BudgetedAppendFile:
    return BudgetedAppendFile(path, _budget, truncate=truncate)


def set_cap(cap: int) -> None:
    _budget.set_cap(cap)


def prometheus_lines() -> str:
    b = _budget
    return (
        "# TYPE pilosa_tpu_wal_fd_cap gauge\n"
        f"pilosa_tpu_wal_fd_cap {b.cap}\n"
        "# TYPE pilosa_tpu_wal_fd_open gauge\n"
        f"pilosa_tpu_wal_fd_open {b.open_count()}\n"
        "# TYPE pilosa_tpu_wal_fd_evictions counter\n"
        f"pilosa_tpu_wal_fd_evictions {b.evictions}\n"
        "# TYPE pilosa_tpu_wal_fd_reopens counter\n"
        f"pilosa_tpu_wal_fd_reopens {b.reopens}\n"
    )
