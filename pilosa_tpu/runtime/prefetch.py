"""Predictive prefetcher: promote host-tier entries back into HBM
ahead of the queries that will want them.

The tiered residency manager (runtime/residency.py) makes a working
set larger than HBM survivable — demoted entries re-promote
asynchronously on demand.  This module makes it FAST for skewed
traffic: the flight recorder's access statistics
(``observe.access_stats`` — every tiered stack access ticks a decayed
per-entry score) rank the demoted entries, and a background loop
submits the hottest ones to the promotion pool as PREFETCH work
before a query stalls on them.  On a zipfian row mix this converts
most would-be promotion waits into plain HBM hits — the
``prefetch.useful`` counter (a query touching a prefetcher-installed
entry) is the direct evidence, and bench.py extras.residency pins the
prefetch-on stall rate strictly below prefetch-off.

Prefetch work is the FIRST thing shed under pressure: the promoter
refuses prefetch jobs on a full queue (and evicts queued prefetch
jobs to make room for demand promotions), and each job runs under
admission's ``internal`` class, so query saturation pauses prefetching
exactly like it pauses compaction.

One Prefetcher per server (the DeviceSampler pattern); the state it
reads — host tier, access scores, promotion pool — is process-wide,
and concurrent prefetchers are harmless (single-flight per key
dedupes)."""

from __future__ import annotations

import threading

from pilosa_tpu import observe as _observe
from pilosa_tpu.runtime import residency as _residency


class Prefetcher:
    """Background promotion-ahead loop ([residency] prefetch /
    prefetch-interval)."""

    #: At most this many prefetch submissions per cycle — the loop
    #: must never saturate the promotion queue it is explicitly the
    #: lowest-priority user of.
    BATCH = 8

    def __init__(self, interval: float | None = None):
        self.interval = interval
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.cycles = 0
        self.issued = 0

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="residency-prefetch")
        self._thread.start()

    def _run(self) -> None:
        while True:
            cfg = _residency.config()
            wait = (self.interval if self.interval is not None
                    else cfg.prefetch_interval)
            if self._stop.wait(max(0.01, wait)):
                return
            try:
                if cfg.prefetch and cfg.host_budget_bytes > 0:
                    self.issued += self.run_once()
                self.cycles += 1
            except Exception:  # noqa: BLE001 — never take the loop down
                pass

    def run_once(self) -> int:
        """One prediction cycle: rank the demoted host-tier entries by
        access score and submit the hottest as prefetch promotions.
        Returns how many jobs were submitted (tests call this directly
        for determinism).

        Two guards keep prediction from becoming churn:

        - zero-scored entries are skipped — promoting something no
          query ever touched is pure queue pressure;
        - a candidate must be strictly HOTTER than the coldest
          currently-resident entry (when the budget is full, every
          promotion evicts someone — displacing a hotter resident
          with a colder demotee would manufacture the very stalls
          prefetching exists to remove).
        """
        mgr = _residency.manager()
        candidates = mgr.host_candidates(64)
        if not candidates:
            return 0
        stats = _observe.access_stats()
        scored = [(stats.score(e.eid), e) for e in candidates]
        scored.sort(key=lambda p: -p[0])
        promoter = _residency.promoter()
        n = 0
        pending = 0  # bytes submitted this cycle, not yet admitted
        for score, ent in scored[:self.BATCH]:
            if score <= 0.0:
                break
            if promoter.queue_full():
                break  # saturated: shed the whole cycle, and DON'T
                #        demote — evicting residents for promotions
                #        that will never run would shrink the warm
                #        set under exactly the pressure prefetch
                #        exists to relieve
            # victim-aware admission: a FULL budget means promoting
            # this candidate evicts SOMEONE — pick the victim by the
            # same access-score signal (demote the coldest resident,
            # BEFORE the submit so the worker's admit lands in the
            # freed budget rather than LRU-evicting on its own; with
            # genuine headroom no demotion is needed at all).  The
            # fullness estimate counts this cycle's own in-flight
            # submissions (``pending``) — their admits land async, so
            # the manager's total alone under-reads and the later
            # promotions of the batch would LRU-evict on their own.
            # Letting plain LRU choose victims displaces
            # hot-but-not-just-now rows and measurably INCREASES
            # stalls on a zipfian mix (see demote_coldest).
            if mgr.total + pending + ent.nbytes > mgr.budget:
                resident = mgr.resident_eids()
                res_scores = {eid: stats.score(eid)
                              for eid in resident}
                if resident and score <= min(res_scores.values()):
                    break  # residents are already the hottest set
                mgr.demote_coldest(res_scores)
            if promoter.submit(ent, prefetch=True) is not None:
                n += 1
                pending += ent.nbytes
        return n

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2)
        self._thread = None

    def stats(self) -> dict:
        return {"running": self._thread is not None
                and self._thread.is_alive(),
                "cycles": self.cycles,
                "issued": self.issued}
