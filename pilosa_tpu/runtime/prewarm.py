"""Background stack prewarm: kill the cold-first-query tail.

The reference eagerly opens + mmaps every fragment at startup
(holder.go:137 -> view.go:117-177), so a restarted server answers its
first query immediately.  Here fragments also load eagerly at open, but
the fused executor path adds one more tier the reference doesn't have:
device/host row stacks assembled on first touch.  At the 10B-column
north-star shape that first touch is ~2 x 1.25 GB of stack assembly —
measured at 18.6 s after a bulk import (the background compaction of
9,537 fresh fragments competes for the same core) — a tail the warm
179 ms steady state never shows (VERDICT round-2 missing #3).

This module shifts that cost off the first query.  Bulk imports and
holder open enqueue the touched field+rows; one background worker
assembles exactly the (row, shards) cache entries the fused path will
look up, so the first query hits warm caches.  The worker is bounded:

  - residency budget: a stack is only built while total usage stays
    under BUDGET_FRACTION of the budget (eviction churn would defeat
    the point);
  - ROW_CAP rows per job, most-frequent first (a bulk import naming
    10k distinct rows must not LRU-thrash the cache with 10k stacks);
  - stacks build through the normal Field entry points, so placement,
    caching, and invalidation are the product path, not a parallel one.

``PILOSA_TPU_PREWARM=0`` disables enqueueing (used to measure the
documented cold floor; tests comparing cold paths can also gate it).
"""

from __future__ import annotations

import os
import queue
import threading

from pilosa_tpu import logger as _logger

ROW_CAP = 128          # stacks per prewarm job, most-frequent rows first
BUDGET_FRACTION = 0.75  # stop building while residency usage is above this
QUEUE_DEPTH = 256

_queue: queue.Queue | None = None
_lock = threading.Lock()
_inflight = 0
_idle = threading.Condition(_lock)
_pending: set[tuple] = set()  # (id(index), field_name) queued, not started

_counters = {
    "stacks_built": 0,
    "rows_skipped_budget": 0,
    "jobs_failed": 0,
}

log: _logger.Logger = _logger.StandardLogger()


def enabled() -> bool:
    return os.environ.get("PILOSA_TPU_PREWARM", "1") != "0"


def bump(name: str, value: int = 1) -> None:
    with _lock:
        _counters[name] += value


def counters() -> dict:
    with _lock:
        return dict(_counters)


def prometheus_lines() -> str:
    out = []
    for name, v in sorted(counters().items()):
        m = f"pilosa_prewarm_{name}_total"
        out.append(f"# TYPE {m} counter")
        out.append(f"{m} {v}")
    return "\n".join(out) + "\n"


def _headroom_ok(extra_bytes: int) -> bool:
    from pilosa_tpu.runtime import residency

    mgr = residency.manager()
    return mgr.total + extra_bytes <= mgr.budget * BUDGET_FRACTION


def _job_rows(field, rows) -> list[int]:
    """Resolve the rows to warm.  Explicit rows come frequency-ordered
    from the import path; ``None`` (holder open) samples row ids from
    the first few fragments — the restart analog of the reference's
    eager mmap, bounded instead of exhaustive."""
    if rows is not None:
        return list(rows)[:ROW_CAP]
    from pilosa_tpu.models.view import VIEW_STANDARD

    view = field.view(VIEW_STANDARD)
    if view is None:
        return []
    out: list[int] = []
    seen: set[int] = set()
    for shard in sorted(view.available_shards())[:4]:
        frag = view.fragment(shard)
        if frag is None:
            continue
        # hottest rows first when the fragment's TopN cache knows them,
        # plain row ids otherwise
        counts = frag.topn_cache.get(frag._gen)
        ids = ([r for r, _ in sorted(counts.items(), key=lambda kv: -kv[1])]
               if counts else frag.row_ids())
        for r in ids:
            if r not in seen:
                seen.add(r)
                out.append(r)
            if len(out) >= ROW_CAP:
                return out
    return out


def _live(index, field) -> bool:
    """A queued job must not rebuild stacks for a deleted field: the
    queue holds strong refs, so a delete landing before the worker
    drains would otherwise re-admit multi-GB buffers into a cache
    nothing ever forgets again."""
    try:
        return index.fields.get(field.name) is field
    except Exception:
        return False


def _run_job(index, field, rows) -> None:
    from pilosa_tpu.models.field import FieldType
    from pilosa_tpu.ops import bitmap as bm
    from pilosa_tpu.shardwidth import SHARD_WIDTH

    if not _live(index, field):
        return
    shards = tuple(sorted(index.available_shards()))
    if not shards:
        return
    stack_bytes = len(shards) * bm.n_words(SHARD_WIDTH) * 4
    if field.options.type == FieldType.INT:
        # BSI queries touch the whole plane stack at once
        if _headroom_ok(stack_bytes * (field.options.bit_depth + 2)):
            field.device_plane_stack(shards)
            bump("stacks_built")
        else:
            bump("rows_skipped_budget")
        return
    for row in _job_rows(field, rows):
        if not _live(index, field):  # delete landed mid-job: stop
            return
        if not _headroom_ok(stack_bytes):
            bump("rows_skipped_budget")
            return  # budget is a hard stop, not a per-row skip
        field.device_row_stack(int(row), shards)
        bump("stacks_built")


def _worker() -> None:
    global _inflight
    while True:
        index, field, rows = _queue.get()
        # release the dedup key at DEQUEUE: an import landing while
        # this job runs carries new rows and must re-queue, not be
        # silently dropped (dedup only collapses back-to-back enqueues
        # of a still-queued job)
        with _lock:
            _pending.discard((id(index), field.name))
        try:
            _run_job(index, field, rows)
        except Exception as e:  # noqa: BLE001 — prewarm must never break serving
            bump("jobs_failed")
            log.printf("prewarm: job for field %r failed (%r); first "
                       "query pays the cold build instead", field.name, e)
        finally:
            with _lock:
                _inflight -= 1
                _idle.notify_all()
            _queue.task_done()


def _ensure_worker() -> None:
    global _queue
    if _queue is not None:
        return
    with _lock:
        if _queue is not None:
            return
        _queue = queue.Queue(maxsize=QUEUE_DEPTH)
        threading.Thread(target=_worker, daemon=True,
                         name="stack-prewarm").start()


def enqueue(index, field, rows=None) -> None:
    """Queue a prewarm job; drops silently when disabled, the queue is
    full (prewarm is best-effort — the first query just pays the build),
    or the same field is already queued."""
    global _inflight
    if not enabled():
        return
    _ensure_worker()
    key = (id(index), field.name)
    with _lock:
        if key in _pending:
            return
        _pending.add(key)
        _inflight += 1
    try:
        _queue.put_nowait((index, field, rows))
    except queue.Full:
        with _lock:
            _pending.discard(key)
            _inflight -= 1
            _idle.notify_all()


def drain(timeout: float | None = 30.0) -> bool:
    """Block until queued prewarm jobs finish (test/measure barrier)."""
    if _queue is None:
        return True
    import time

    deadline = None if timeout is None else time.monotonic() + timeout
    with _idle:
        while _inflight > 0:
            if deadline is None:
                _idle.wait(timeout=1.0)
                continue
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            _idle.wait(timeout=remaining)
    return True
