"""Generation-stamped query result cache: repeated reads skip the
device entirely.

VERDICT round 5 established the Count/Intersect hot path is
dispatch-bound, not HBM-bound (a ~20 us trivial-dispatch floor under a
0.555 ms/query chip capture, bw_util 0.148) — so for read-heavy traffic
the biggest remaining win is to not launch at all.  The reference ships
only the per-fragment rank cache (cache.go:136, ported as
models/cache.py with exact generation-stamped counts); this module
generalizes the same idiom to whole PQL subtrees, the classic
recomputation-vs-retained-state trade of the Roaring line of work
(Chambi et al.; Lemire et al., "Roaring Bitmaps: Implementation of an
Optimized Software Library").

One process-wide, memory-budgeted LRU cache maps a canonical query key
— (holder identity, index, root kind, fused expression shape with leaf
identities ``(field, view, row)`` substituted at the slots, shard set)
— to its result, stamped with the participating fragments' generation
state: per (field, view) an aggregate ``(count, sum_gen, sum_uid,
max_uid)`` over the shard set (change-detecting under the monotone
uid/gen discipline — see ``Executor._rc_collect_gens``).
**Invalidation is free**: every mutation path bumps the fragment
generation (import, import-value, import-roaring, Set/Clear, Store,
ClearRow, BSI set/clear-value — audited by tests/test_resultcache.py),
so a stale entry simply misses, exactly like ``TopNCache.get(gen)``
today.  The uid components make a fragment replaced by resize/restore
(a NEW object whose ``_gen`` can collide) unhittable.

Stamp-before-read discipline (the correctness core): callers capture
the generation tuple BEFORE reading any fragment data, and fill with
that same stamp.  A mutation that lands between capture and read
leaves the entry stamped with the OLD generations while the live
fragments carry new ones — the entry can never be served, only
refilled.  The reverse order (stamp after read) would serve stale data
and is therefore forbidden.

Results live on host: Count totals and per-shard count tuples are a
few machine words, TopN/GroupBy results small dicts, Row results numpy
word-array copies accounted against this cache's own byte budget
(separate from the device ResidencyManager budget — an evicted result
recomputes from the still-resident device stacks, so eviction here
costs one dispatch, not a transfer).

Surface: ``[cache]`` config (budget bytes, max entry bytes, ttl,
enabled), ``?nocache=1`` on the query route (symmetric with
``?nocoalesce``), ``cached``/``cacheKey`` on every flight record,
``cache.{hits,misses,fills,evictions,invalidations,bytes}`` gauge
families on /metrics, and ``GET /debug/resultcache``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import time


#: Defaults; the server assembly reconfigures from [cache] config.
DEFAULT_BUDGET_BYTES = 128 << 20
DEFAULT_MAX_ENTRY_BYTES = 8 << 20

#: Accounting floor per entry: key tuple + stamp tuple + dict slot.
#: Prevents a flood of "free" scalar entries from reading as zero
#: bytes while really holding megabytes of Python structure.
ENTRY_OVERHEAD_BYTES = 256


class Key:
    """Hash-once wrapper for cache keys.  A key is a deep nested tuple
    whose tail is the full shard tuple (256+ ints at production shard
    counts), and tuples do not cache their hash — the probe's
    get / pop / insert sequence would rehash it three times.  Wrapping
    computes it once; equality (only reached when hashes already
    match) delegates to the C tuple compare."""

    __slots__ = ("k", "h")

    def __init__(self, k):
        self.k = k
        self.h = hash(k)

    def __hash__(self) -> int:
        return self.h

    def __eq__(self, other):
        if self is other:
            return True
        if isinstance(other, Key):
            return self.k == other.k
        return NotImplemented

    def __repr__(self) -> str:  # key_digest / debug stability
        return repr(self.k)


class _Entry:
    __slots__ = ("gens", "value", "nbytes", "t", "hits")

    def __init__(self, gens, value, nbytes: int):
        self.gens = gens
        self.value = value
        self.nbytes = nbytes
        self.t = time.monotonic()
        self.hits = 0


class ResultCache:
    """Memory-budgeted LRU of generation-stamped query results.

    ``get(key, gens)`` hits only when the stored stamp equals the
    caller's freshly-computed generation tuple; a mismatched entry is
    dropped on the spot (counted as an invalidation) so mutated keys
    free their bytes immediately instead of waiting for LRU churn.
    ``put`` enforces the byte budget strictly — the cache NEVER holds
    more than ``budget`` bytes, even transiently after the insert
    (acceptance: the churn test mirrors test_residency's tiny-budget
    pattern)."""

    def __init__(self, budget_bytes: int = DEFAULT_BUDGET_BYTES,
                 max_entry_bytes: int = DEFAULT_MAX_ENTRY_BYTES,
                 ttl_s: float = 0.0, enabled: bool = True):
        self.budget = int(budget_bytes)
        self.max_entry_bytes = int(max_entry_bytes)
        self.ttl_s = float(ttl_s)
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        # insertion order == LRU order (move-to-end on hit)
        self._entries: dict = {}
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.fills = 0
        self.evictions = 0
        self.invalidations = 0
        self.skipped_oversize = 0

    # -------------------------------------------------------------- access

    def get(self, key, gens) -> tuple[bool, object]:
        """(hit, value).  ``gens`` is the CURRENT generation tuple the
        caller just computed from the live fragments; a stored stamp
        that differs means some participating fragment mutated (or was
        replaced) since the fill — the entry is dropped and the call
        counts as a miss."""
        if not self.enabled:
            return False, None
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.misses += 1
                return False, None
            if e.gens != gens or (
                    self.ttl_s > 0
                    and time.monotonic() - e.t > self.ttl_s):
                del self._entries[key]
                self.bytes -= e.nbytes
                self.invalidations += 1
                self.misses += 1
                return False, None
            self._entries[key] = self._entries.pop(key)  # move-to-end
            e.hits += 1
            self.hits += 1
            return True, e.value

    def put(self, key, gens, value, nbytes: int) -> bool:
        """Insert one result stamped with the generations captured
        BEFORE its inputs were read.  Returns False when the entry was
        refused (disabled / oversize / bigger than the whole budget)."""
        if not self.enabled:
            return False
        nbytes = int(nbytes) + ENTRY_OVERHEAD_BYTES
        if nbytes > self.max_entry_bytes or nbytes > self.budget:
            with self._lock:
                self.skipped_oversize += 1
            return False
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self.bytes -= old.nbytes
            self._entries[key] = _Entry(gens, value, nbytes)
            self.bytes += nbytes
            self.fills += 1
            # strict budget: evict LRU until under — the entry just
            # inserted is newest and falls last, and since it fits the
            # budget on its own (checked above) the loop terminates
            # with it retained
            while self.bytes > self.budget and self._entries:
                vk = next(iter(self._entries))
                ve = self._entries.pop(vk)
                self.bytes -= ve.nbytes
                self.evictions += 1
            return True

    def invalidate_all(self) -> int:
        """Drop everything (operator escape hatch / tests).  Counted
        as invalidations."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self.bytes = 0
            self.invalidations += n
            return n

    # ------------------------------------------------------------- exports

    def stats_dict(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "budget": self.budget,
                "maxEntryBytes": self.max_entry_bytes,
                "ttlS": self.ttl_s,
                "bytes": self.bytes,
                "entries": len(self._entries),
                "hits": self.hits,
                "misses": self.misses,
                "fills": self.fills,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
                "skippedOversize": self.skipped_oversize,
            }

    def debug(self, top_n: int = 32) -> dict:
        """The /debug/resultcache document: totals plus the largest
        entries (key digest + human-readable key, bytes, age, hits)."""
        out = self.stats_dict()
        now = time.monotonic()
        with self._lock:
            entries = sorted(self._entries.items(),
                             key=lambda kv: -kv[1].nbytes)[:top_n]
            out["top"] = [{
                "key": key_digest(k),
                "repr": repr(k)[:200],
                "bytes": e.nbytes,
                "ageS": round(now - e.t, 3),
                "hits": e.hits,
            } for k, e in entries]
        return out

    def publish_gauges(self, stats) -> None:
        """Push the cache.* families into a stats registry at scrape
        time (/metrics, /debug/vars).  Cumulative totals render as
        gauges, not counters — re-publishing a cumulative value
        through a counter would double-count (same rule as
        devobs.publish_gauges)."""
        s = self.stats_dict()
        stats.gauge("cache.hits", s["hits"])
        stats.gauge("cache.misses", s["misses"])
        stats.gauge("cache.fills", s["fills"])
        stats.gauge("cache.evictions", s["evictions"])
        stats.gauge("cache.invalidations", s["invalidations"])
        stats.gauge("cache.bytes", s["bytes"])
        stats.gauge("cache.entries", s["entries"])
        stats.gauge("cache.budget_bytes", s["budget"])


def key_digest(key) -> str:
    """Stable short digest of a cache key for flight records and the
    debug surface (the full tuple is structured but verbose)."""
    return hashlib.blake2b(repr(key).encode(),
                           digest_size=8).hexdigest()


def result_nbytes(value) -> int:
    """Byte estimate for one cached result: numpy buffers by .nbytes,
    containers and result dataclasses (GroupCount rows of FieldRow,
    Pair, ValCount...) recursively, scalars a machine word.  An
    estimate — the budget bounds order-of-magnitude memory, not
    malloc'd bytes.  Charging a GroupCount as a bare scalar would let
    a GroupBy-heavy workload exceed the budget by an order of
    magnitude in real memory, so dataclasses recurse into their
    fields."""
    nb = getattr(value, "nbytes", None)
    if nb is not None:
        return int(nb)
    if isinstance(value, dict):
        return 64 + sum(result_nbytes(k) + result_nbytes(v)
                        for k, v in value.items())
    if isinstance(value, (list, tuple)):
        return 64 + sum(result_nbytes(v) for v in value)
    if isinstance(value, (bytes, str)):
        return 48 + len(value)
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return 64 + sum(
            result_nbytes(getattr(value, f.name))
            for f in dataclasses.fields(value))
    return 32


# ----------------------------------------------------------- process-wide


_global: ResultCache | None = None
_global_lock = threading.Lock()


def cache() -> ResultCache:
    """The process-wide cache (one budget per process, like the
    residency manager and the jit caches the results shortcut).
    Lock-free on the hot path — every query probe calls this; the
    lock only guards first construction."""
    global _global
    c = _global
    if c is not None:
        return c
    with _global_lock:
        if _global is None:
            _global = ResultCache()
        return _global


def configure(budget_bytes: int | None = None,
              max_entry_bytes: int | None = None,
              ttl_s: float | None = None,
              enabled: bool | None = None) -> ResultCache:
    """Apply [cache] config to the process-wide cache in place
    (counters and live entries survive — a second in-process server
    must not wipe the first's warm cache)."""
    c = cache()
    with c._lock:
        if budget_bytes is not None:
            c.budget = int(budget_bytes)
        if max_entry_bytes is not None:
            c.max_entry_bytes = int(max_entry_bytes)
        if ttl_s is not None:
            c.ttl_s = float(ttl_s)
        if enabled is not None:
            c.enabled = bool(enabled)
    return c


def reset(budget_bytes: int = DEFAULT_BUDGET_BYTES,
          max_entry_bytes: int = DEFAULT_MAX_ENTRY_BYTES,
          ttl_s: float = 0.0, enabled: bool = True) -> ResultCache:
    """Replace the process-wide cache (tests)."""
    global _global
    with _global_lock:
        _global = ResultCache(budget_bytes, max_entry_bytes, ttl_s,
                              enabled)
        return _global
