"""Generation-stamped query result cache: repeated reads skip the
device entirely.

VERDICT round 5 established the Count/Intersect hot path is
dispatch-bound, not HBM-bound (a ~20 us trivial-dispatch floor under a
0.555 ms/query chip capture, bw_util 0.148) — so for read-heavy traffic
the biggest remaining win is to not launch at all.  The reference ships
only the per-fragment rank cache (cache.go:136, ported as
models/cache.py with exact generation-stamped counts); this module
generalizes the same idiom to whole PQL subtrees, the classic
recomputation-vs-retained-state trade of the Roaring line of work
(Chambi et al.; Lemire et al., "Roaring Bitmaps: Implementation of an
Optimized Software Library").

One process-wide, memory-budgeted LRU cache maps a canonical query key
— (holder identity, index, root kind, fused expression shape with leaf
identities ``(field, view, row)`` substituted at the slots, shard set)
— to its result, stamped with the participating fragments' generation
state: per (field, view) an aggregate ``(count, sum_gen, sum_uid,
max_uid)`` over the shard set (change-detecting under the monotone
uid/gen discipline — see ``Executor._rc_collect_gens``).
**Invalidation is free**: every mutation path bumps the fragment
generation (import, import-value, import-roaring, Set/Clear, Store,
ClearRow, BSI set/clear-value — audited by tests/test_resultcache.py),
so a stale entry simply misses, exactly like ``TopNCache.get(gen)``
today.  The uid components make a fragment replaced by resize/restore
(a NEW object whose ``_gen`` can collide) unhittable.

Stamp-before-read discipline (the correctness core): callers capture
the generation tuple BEFORE reading any fragment data, and fill with
that same stamp.  A mutation that lands between capture and read
leaves the entry stamped with the OLD generations while the live
fragments carry new ones — the entry can never be served, only
refilled.  The reverse order (stamp after read) would serve stale data
and is therefore forbidden.

Results live on host: Count totals and per-shard count tuples are a
few machine words, TopN/GroupBy results small dicts, Row results numpy
word-array copies accounted against this cache's own byte budget
(separate from the device ResidencyManager budget — an evicted result
recomputes from the still-resident device stacks, so eviction here
costs one dispatch, not a transfer).

Single-flight fills (the streaming-ingest round): under sustained
ingest every delta write invalidates its key, and all concurrently
arriving readers miss TOGETHER — without coordination each one
re-executes the identical query, multiplying device work by the
convoy depth exactly when the system is busiest (the classic cache
stampede).  ``get`` therefore registers the FIRST misser of a
``(key, stamp)`` as the flight leader; same-stamp missers arriving
while the flight is open wait (bounded by ``FLIGHT_WAIT_S`` and the
flight's age) for the leader's ``put`` and then serve the fill as a
hit.  A leader that dies never wedges followers: the wait is bounded,
an expired flight (``FLIGHT_TTL_S``) is replaced by the next misser,
and a waiter whose wait runs out simply computes — the fallback is
the uncoordinated behavior, never an error.  A stamp moved by a newer
write never joins an older flight (and vice versa): mismatched stamps
compute independently, so single-flight can not serve stale data.

Per-tenant soft budgets (the [tenants] round, serve/tenant.py): with
isolation enabled every entry is charged to the tenant that filled it
(the executor's thread-local tenant scope), each tenant's soft budget
is its ``cache_share`` of the global budget, and the eviction loop
prefers the oldest entry OF AN OVER-BUDGET TENANT before touching the
global LRU order — so one tenant churning distinct keys evicts its own
entries, never the fleet's warm head.  Budgets are soft (a tenant may
transiently exceed its share when the cache has global headroom); the
global budget stays strict.  With [tenants] off the tenant structures
are never consulted — byte-identical behavior, regression-pinned.

Surface: ``[cache]`` config (budget bytes, max entry bytes, ttl,
enabled), ``?nocache=1`` on the query route (symmetric with
``?nocoalesce``), ``cached``/``cacheKey`` on every flight record,
``cache.{hits,misses,fills,evictions,invalidations,bytes,
flight_joins,flight_served}`` gauge families on /metrics, per-tenant
bytes/hit-rates on ``GET /debug/tenants``, and
``GET /debug/resultcache``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import time
from typing import Any

from pilosa_tpu.serve import tenant as _tenant


#: Defaults; the server assembly reconfigures from [cache] config.
DEFAULT_BUDGET_BYTES = 128 << 20
DEFAULT_MAX_ENTRY_BYTES = 8 << 20

#: Accounting floor per entry: key tuple + stamp tuple + dict slot.
#: Prevents a flood of "free" scalar entries from reading as zero
#: bytes while really holding megabytes of Python structure.
ENTRY_OVERHEAD_BYTES = 256

#: How long a same-stamp misser waits for an open flight's fill before
#: giving up and computing itself.  Fills normally land in
#: milliseconds; the cap only matters when the leader is wedged.
FLIGHT_WAIT_S = 1.0

#: A flight older than this is presumed dead (leader errored without
#: filling) and is replaced by the next misser.
FLIGHT_TTL_S = 5.0


class Key:
    """Hash-once wrapper for cache keys.  A key is a deep nested tuple
    whose tail is the full shard tuple (256+ ints at production shard
    counts), and tuples do not cache their hash — the probe's
    get / pop / insert sequence would rehash it three times.  Wrapping
    computes it once; equality (only reached when hashes already
    match) delegates to the C tuple compare.

    Executor keys fold in the mesh placement token
    (``meshexec.placement_token``) so a count computed under one
    device placement never answers a probe made under another — a
    mesh reshape (or mesh on/off flip) naturally misses instead of
    serving a stale single-device result."""

    __slots__ = ("k", "h")

    def __init__(self, k: Any) -> None:
        self.k = k
        self.h = hash(k)

    def __hash__(self) -> int:
        return self.h

    def __eq__(self, other: object) -> bool:
        if self is other:
            return True
        if isinstance(other, Key):
            return self.k == other.k
        return NotImplemented

    def __repr__(self) -> str:  # key_digest / debug stability
        return repr(self.k)


class _Entry:
    __slots__ = ("gens", "value", "nbytes", "t", "hits", "tenant")

    def __init__(self, gens: Any, value: object, nbytes: int,
                 tenant: str | None = None) -> None:
        self.gens = gens
        self.value = value
        self.nbytes = nbytes
        self.t = time.monotonic()
        self.hits = 0
        self.tenant = tenant


class _Flight:
    """One in-progress fill: the leader computes, same-stamp missers
    wait on the event.  ``put`` (any outcome, including an oversize
    refusal) resolves it.  ``tid`` identifies the leader — a thread
    never waits on its own flight (a leader re-probing before its
    fill, e.g. a retried miss, must compute, not self-deadlock)."""

    __slots__ = ("gens", "t0", "event", "tid")

    def __init__(self, gens: Any) -> None:
        self.gens = gens
        self.t0 = time.monotonic()
        self.event = threading.Event()
        self.tid = threading.get_ident()


class ResultCache:
    """Memory-budgeted LRU of generation-stamped query results.

    ``get(key, gens)`` hits only when the stored stamp equals the
    caller's freshly-computed generation tuple; a mismatched entry is
    dropped on the spot (counted as an invalidation) so mutated keys
    free their bytes immediately instead of waiting for LRU churn.
    ``put`` enforces the byte budget strictly — the cache NEVER holds
    more than ``budget`` bytes, even transiently after the insert
    (acceptance: the churn test mirrors test_residency's tiny-budget
    pattern)."""

    def __init__(self, budget_bytes: int = DEFAULT_BUDGET_BYTES,
                 max_entry_bytes: int = DEFAULT_MAX_ENTRY_BYTES,
                 ttl_s: float = 0.0, enabled: bool = True) -> None:
        self.budget = int(budget_bytes)
        self.max_entry_bytes = int(max_entry_bytes)
        self.ttl_s = float(ttl_s)
        self.enabled = bool(enabled)
        from pilosa_tpu import lockcheck

        self._lock = lockcheck.lock("resultcache")
        # insertion order == LRU order (move-to-end on hit)
        self._entries: dict[Any, _Entry] = {}
        #: key -> _Flight: fills in progress (single-flight gate)
        self._flights: dict[Any, _Flight] = {}
        #: keys whose last fill was refused as oversize — such a key
        #: can never serve a flight's waiters, so followers must not
        #: queue behind a leader whose put is doomed (bounded FIFO;
        #: a later successful fill readmits the key)
        self._noflight: dict[Any, None] = {}
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.fills = 0
        self.evictions = 0
        self.invalidations = 0
        self.skipped_oversize = 0
        self.flight_joins = 0
        self.flight_served = 0
        # ---------------- per-tenant accounting ([tenants]) --------
        # tenant -> live bytes; tenant -> ordered key set (per-tenant
        # LRU, mirroring the global order's move-to-end); tenant ->
        # [hits, misses, fills, evictions].  Touched only while a
        # tenant id is attributable (isolation on) — the anonymous
        # path never pays the dict ops.
        self._tenant_bytes: dict[str, int] = {}
        self._tenant_lru: dict[str, dict] = {}
        self._tenant_counters: dict[str, list] = {}
        self.tenant_pref_evictions = 0  # over-budget-tenant victims

    # ------------------------------------------------- tenant helpers

    @staticmethod
    def _caller_tenant(tenant: str | None) -> str | None:
        """The tenant this access charges against: an explicit id, or
        the executor's thread-local scope — None (no accounting at
        all) while [tenants] isolation is off."""
        if not _tenant.enabled():
            # no accounting at all while isolation is off — returning
            # an explicit label here would mint per-label dict keys
            # from unauthenticated traffic on the DEFAULT config
            return None
        # explicit ids (coalescer fills) pass the individuation bound
        # too, so rotated labels collapse consistently
        return _tenant.resolve(tenant if tenant is not None
                               else _tenant.current())

    def _tc_locked(self, t: str) -> list:
        c = self._tenant_counters.get(t)
        if c is None:
            c = self._tenant_counters[t] = [0, 0, 0, 0]
        return c

    def _tenant_track_locked(self, key: Any, e: _Entry) -> None:
        if e.tenant is None:
            return
        self._tenant_bytes[e.tenant] = \
            self._tenant_bytes.get(e.tenant, 0) + e.nbytes
        self._tenant_lru.setdefault(e.tenant, {})[key] = None

    def _tenant_untrack_locked(self, key: Any, e: _Entry) -> None:
        if e.tenant is None:
            return
        self._tenant_bytes[e.tenant] = \
            self._tenant_bytes.get(e.tenant, 0) - e.nbytes
        lru = self._tenant_lru.get(e.tenant)
        if lru is not None:
            lru.pop(key, None)

    def _tenant_touch_locked(self, key: Any, e: _Entry) -> None:
        if e.tenant is None:
            return
        lru = self._tenant_lru.get(e.tenant)
        if lru is not None and key in lru:
            lru[key] = lru.pop(key)

    def _victim_key_locked(self, protect: Any) -> Any:
        """The next eviction victim: the oldest entry of any tenant
        over its soft budget (its churn evicts ITS OWN entries first —
        the isolation contract), else the global LRU head.  Never the
        entry being inserted (``protect``)."""
        pol = _tenant.policy()
        if pol is not None and self._tenant_bytes:
            for t, b in self._tenant_bytes.items():
                if b <= int(self.budget * pol.quota_for(t).cache_share):
                    continue
                for k in self._tenant_lru.get(t, ()):
                    if k != protect:
                        self.tenant_pref_evictions += 1
                        return k
        for k in self._entries:
            if k != protect:
                return k
        return None

    # -------------------------------------------------------- access

    def get(self, key: Any, gens: Any,
            wait_s: float = FLIGHT_WAIT_S,
            tenant: str | None = None) -> tuple[bool, object]:
        """(hit, value).  ``gens`` is the CURRENT generation tuple the
        caller just computed from the live fragments; a stored stamp
        that differs means some participating fragment mutated (or was
        replaced) since the fill — the entry is dropped and the call
        counts as a miss.

        Single-flight: a miss with no open same-stamp flight registers
        one (the caller is the leader and is expected to ``put``); a
        miss while a same-stamp fill is already in progress waits up
        to ``wait_s`` for it and serves the fill as a hit.  Pass
        ``wait_s=0`` to never wait (pure probe)."""
        if not self.enabled:
            return False, None
        t = self._caller_tenant(tenant)
        budget = wait_s
        while True:
            with self._lock:
                e = self._entries.get(key)
                if e is not None:
                    if e.gens == gens and not (
                            self.ttl_s > 0
                            and time.monotonic() - e.t > self.ttl_s):
                        self._entries[key] = self._entries.pop(key)
                        self._tenant_touch_locked(key, e)
                        e.hits += 1
                        self.hits += 1
                        if t is not None:
                            self._tc_locked(t)[0] += 1
                        return True, e.value
                    del self._entries[key]
                    self.bytes -= e.nbytes
                    self._tenant_untrack_locked(key, e)
                    self.invalidations += 1
                if key in self._noflight:
                    # last fill for this key was refused (oversize):
                    # waiting could never turn into a hit
                    self.misses += 1
                    if t is not None:
                        self._tc_locked(t)[1] += 1
                    return False, None
                fl = self._flights.get(key)
                now = time.monotonic()
                if (fl is None or fl.gens != gens
                        or fl.tid == threading.get_ident()
                        or now - fl.t0 > FLIGHT_TTL_S):
                    # no joinable fill: this caller computes.  A
                    # mismatched-stamp flight is left to its own
                    # waiters (its fill will simply never match ours);
                    # an expired one is presumed dead and replaced;
                    # our own open flight means WE are the leader.
                    if fl is None or now - fl.t0 > FLIGHT_TTL_S:
                        # leaders that die before put() (query error,
                        # deadline expiry) leave orphans only a
                        # same-key miss would replace — sweep expired
                        # flights here so diverse errored keys cannot
                        # grow the registry without bound
                        if len(self._flights) >= 64:
                            for k in [k for k, f in self._flights.items()
                                      if now - f.t0 > FLIGHT_TTL_S]:
                                self._flights.pop(k).event.set()
                        self._flights[key] = _Flight(gens)
                    self.misses += 1
                    if t is not None:
                        self._tc_locked(t)[1] += 1
                    return False, None
                if budget <= 0:
                    # joinable fill but the caller can't wait
                    self.misses += 1
                    if t is not None:
                        self._tc_locked(t)[1] += 1
                    return False, None
                self.flight_joins += 1
                remaining = min(budget, FLIGHT_TTL_S - (now - fl.t0))
            t0 = time.monotonic()
            filled = fl.event.wait(remaining)
            budget -= time.monotonic() - t0
            if filled:
                # loop re-probes: the normal outcome is a hit on the
                # leader's fill (counted below as flight_served); a
                # refused fill (oversize) falls through to computing
                with self._lock:
                    e = self._entries.get(key)
                    if e is not None and e.gens == gens:
                        self._entries[key] = self._entries.pop(key)
                        self._tenant_touch_locked(key, e)
                        e.hits += 1
                        self.hits += 1
                        if t is not None:
                            self._tc_locked(t)[0] += 1
                        self.flight_served += 1
                        return True, e.value
                    budget = 0  # resolved without a usable fill
            # timed out (or unusable fill): compute ourselves on the
            # next pass — budget is spent, so the re-entry can't wait

    def put(self, key: Any, gens: Any, value: object,
            nbytes: int, tenant: str | None = None) -> bool:
        """Insert one result stamped with the generations captured
        BEFORE its inputs were read.  Returns False when the entry was
        refused (disabled / oversize / bigger than the whole budget).
        Every outcome resolves an open flight for the key — waiters
        must never outlive their leader's attempt.  With [tenants]
        isolation on, the fill is charged to ``tenant`` (or the
        thread-local tenant scope) and eviction prefers over-budget
        tenants' own entries."""
        if not self.enabled:
            return False
        from pilosa_tpu import faultinject as _fi

        if _fi.armed:
            # failpoint: the production cache-fill path (an injected
            # error here surfaces to the filling query; waiters'
            # bounded flight wait covers the unresolved flight)
            _fi.hit("resultcache.fill")
        t = self._caller_tenant(tenant)
        nbytes = int(nbytes) + ENTRY_OVERHEAD_BYTES
        if nbytes > self.max_entry_bytes or nbytes > self.budget:
            with self._lock:
                self.skipped_oversize += 1
                self._resolve_flight_locked(key)
                self._noflight[key] = None
                while len(self._noflight) > 256:
                    self._noflight.pop(next(iter(self._noflight)))
            return False
        with self._lock:
            self._noflight.pop(key, None)
            old = self._entries.pop(key, None)
            if old is not None:
                self.bytes -= old.nbytes
                self._tenant_untrack_locked(key, old)
            e = _Entry(gens, value, nbytes, tenant=t)
            self._entries[key] = e
            self.bytes += nbytes
            self._tenant_track_locked(key, e)
            self.fills += 1
            if t is not None:
                self._tc_locked(t)[2] += 1
            self._resolve_flight_locked(key)
            # strict budget: evict until under — over-budget tenants'
            # oldest entries first (their churn displaces themselves),
            # then global LRU.  The entry just inserted is never a
            # victim, and since it fits the budget on its own (checked
            # above) the loop terminates with it retained.
            while self.bytes > self.budget and len(self._entries) > 1:
                vk = self._victim_key_locked(key)
                if vk is None:
                    break
                ve = self._entries.pop(vk)
                self.bytes -= ve.nbytes
                self._tenant_untrack_locked(vk, ve)
                self.evictions += 1
                if ve.tenant is not None:
                    self._tc_locked(ve.tenant)[3] += 1
            return True

    def _resolve_flight_locked(self, key: Any) -> None:
        fl = self._flights.pop(key, None)
        if fl is not None:
            fl.event.set()

    def invalidate_shard(self, index: str, shard: int) -> int:
        """Drop every entry whose key covers ``shard`` of ``index`` —
        the rebalance cutover hook.  Generation stamps alone do NOT
        cover an ownership change: the local fragments never mutated,
        so a node that just lost (or gained) a shard would keep
        serving its remote-map entries verbatim.  Executor keys are
        ``(holder_uid, index, kind, sig, extra, shards, placement)``
        (see Executor._rc_probe); foreign key shapes are left alone.
        Dropped keys resolve their open flights so waiters recompute
        instead of waiting on a fill for an evicted key."""
        shard = int(shard)
        with self._lock:
            victims = []
            for key, e in self._entries.items():
                k = getattr(key, "k", key)
                if (isinstance(k, tuple) and len(k) >= 6
                        and k[1] == index
                        and isinstance(k[5], tuple) and shard in k[5]):
                    victims.append((key, e))
            for key, e in victims:
                del self._entries[key]
                self.bytes -= e.nbytes
                self._tenant_untrack_locked(key, e)
                self._resolve_flight_locked(key)
            self.invalidations += len(victims)
            return len(victims)

    def invalidate_all(self) -> int:
        """Drop everything (operator escape hatch / tests).  Counted
        as invalidations.  Open flights resolve (waiters wake, miss,
        and compute) rather than linger against cleared entries."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self.bytes = 0
            self._tenant_bytes.clear()
            self._tenant_lru.clear()
            self.invalidations += n
            for fl in self._flights.values():
                fl.event.set()
            self._flights.clear()
            return n

    # ------------------------------------------------------------- exports

    def stats_dict(self) -> dict[str, Any]:
        with self._lock:
            return {
                "enabled": self.enabled,
                "budget": self.budget,
                "maxEntryBytes": self.max_entry_bytes,
                "ttlS": self.ttl_s,
                "bytes": self.bytes,
                "entries": len(self._entries),
                "hits": self.hits,
                "misses": self.misses,
                "fills": self.fills,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
                "skippedOversize": self.skipped_oversize,
                "flightJoins": self.flight_joins,
                "flightServed": self.flight_served,
                "flightsOpen": len(self._flights),
                "tenantPrefEvictions": self.tenant_pref_evictions,
            }

    def tenant_stats(self) -> dict[str, dict]:
        """Per-tenant cache accounting — the result-cache half of
        GET /debug/tenants: live bytes, soft budget, and the
        hit/miss/fill/eviction counters an abusive-tenant triage
        reads.  Empty until a tenant-attributed access happens."""
        pol = _tenant.policy()
        out: dict[str, dict] = {}
        with self._lock:
            names = set(self._tenant_bytes) | set(self._tenant_counters)
            for t in sorted(names):
                c = self._tenant_counters.get(t, [0, 0, 0, 0])
                d = {
                    "bytes": self._tenant_bytes.get(t, 0),
                    "entries": len(self._tenant_lru.get(t, ())),
                    "hits": c[0],
                    "misses": c[1],
                    "fills": c[2],
                    "evictions": c[3],
                }
                if pol is not None:
                    d["softBudget"] = int(
                        self.budget * pol.quota_for(t).cache_share)
                out[t] = d
        return out

    def debug(self, top_n: int = 32) -> dict[str, Any]:
        """The /debug/resultcache document: totals plus the largest
        entries (key digest + human-readable key, bytes, age, hits)."""
        out = self.stats_dict()
        tstats = self.tenant_stats()
        if tstats:
            out["tenants"] = tstats
        now = time.monotonic()
        with self._lock:
            entries = sorted(self._entries.items(),
                             key=lambda kv: -kv[1].nbytes)[:top_n]
            out["top"] = [{
                "key": key_digest(k),
                "repr": repr(k)[:200],
                "bytes": e.nbytes,
                "ageS": round(now - e.t, 3),
                "hits": e.hits,
            } for k, e in entries]
        return out

    def publish_gauges(self, stats: Any) -> None:
        """Push the cache.* families into a stats registry at scrape
        time (/metrics, /debug/vars).  Cumulative totals render as
        gauges, not counters — re-publishing a cumulative value
        through a counter would double-count (same rule as
        devobs.publish_gauges)."""
        s = self.stats_dict()
        stats.gauge("cache.hits", s["hits"])
        stats.gauge("cache.misses", s["misses"])
        stats.gauge("cache.fills", s["fills"])
        stats.gauge("cache.evictions", s["evictions"])
        stats.gauge("cache.invalidations", s["invalidations"])
        stats.gauge("cache.bytes", s["bytes"])
        stats.gauge("cache.entries", s["entries"])
        stats.gauge("cache.budget_bytes", s["budget"])
        stats.gauge("cache.flight_joins", s["flightJoins"])
        stats.gauge("cache.flight_served", s["flightServed"])


def key_digest(key: Any) -> str:
    """Stable short digest of a cache key for flight records and the
    debug surface (the full tuple is structured but verbose)."""
    return hashlib.blake2b(repr(key).encode(),
                           digest_size=8).hexdigest()


def result_nbytes(value: Any) -> int:
    """Byte estimate for one cached result: numpy buffers by .nbytes,
    containers and result dataclasses (GroupCount rows of FieldRow,
    Pair, ValCount...) recursively, scalars a machine word.  An
    estimate — the budget bounds order-of-magnitude memory, not
    malloc'd bytes.  Charging a GroupCount as a bare scalar would let
    a GroupBy-heavy workload exceed the budget by an order of
    magnitude in real memory, so dataclasses recurse into their
    fields."""
    nb = getattr(value, "nbytes", None)
    if nb is not None:
        return int(nb)
    if isinstance(value, dict):
        return 64 + sum(result_nbytes(k) + result_nbytes(v)
                        for k, v in value.items())
    if isinstance(value, (list, tuple)):
        return 64 + sum(result_nbytes(v) for v in value)
    if isinstance(value, (bytes, str)):
        return 48 + len(value)
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return 64 + sum(
            result_nbytes(getattr(value, f.name))
            for f in dataclasses.fields(value))
    return 32


# ----------------------------------------------------------- process-wide


_global: ResultCache | None = None
_global_lock = threading.Lock()


def cache() -> ResultCache:
    """The process-wide cache (one budget per process, like the
    residency manager and the jit caches the results shortcut).
    Lock-free on the hot path — every query probe calls this; the
    lock only guards first construction."""
    global _global
    c = _global
    if c is not None:
        return c
    with _global_lock:
        if _global is None:
            _global = ResultCache()
        return _global


def configure(budget_bytes: int | None = None,
              max_entry_bytes: int | None = None,
              ttl_s: float | None = None,
              enabled: bool | None = None) -> ResultCache:
    """Apply [cache] config to the process-wide cache in place
    (counters and live entries survive — a second in-process server
    must not wipe the first's warm cache)."""
    c = cache()
    with c._lock:
        if budget_bytes is not None:
            c.budget = int(budget_bytes)
        if max_entry_bytes is not None:
            c.max_entry_bytes = int(max_entry_bytes)
        if ttl_s is not None:
            c.ttl_s = float(ttl_s)
        if enabled is not None:
            c.enabled = bool(enabled)
    return c


def reset(budget_bytes: int = DEFAULT_BUDGET_BYTES,
          max_entry_bytes: int = DEFAULT_MAX_ENTRY_BYTES,
          ttl_s: float = 0.0, enabled: bool = True) -> ResultCache:
    """Replace the process-wide cache (tests)."""
    global _global
    with _global_lock:
        _global = ResultCache(budget_bytes, max_entry_bytes, ttl_s,
                              enabled)
        return _global
