"""Background snapshot queue: writes never stall on compaction.

The reference queues fragment snapshots on a 100-deep channel drained by
2 workers (holder.go:163, fragment.go:187-208) so a write that trips the
opN threshold enqueues the compaction and returns.  Same design here,
process-wide (one holder per process in practice, like the residency
manager): ``enqueue(frag)`` marks the fragment pending and hands it to a
worker; a full queue degrades to an inline snapshot (bounded memory, the
write that overflows pays the cost); ``drain()`` blocks until the queue
is empty — holder close and tests use it as a barrier.

Durability does not depend on the queue at all: every mutation is in the
WAL until ``snapshot()`` itself truncates it, so a crash at ANY point
before/during/after the background compaction replays losslessly (the
same guarantee as the reference's in-file op-log, roaring.go:1612).
"""

from __future__ import annotations

import queue
import threading

QUEUE_DEPTH = 100
N_WORKERS = 2

_queue: queue.Queue | None = None
_workers: list[threading.Thread] = []
_lock = threading.Lock()
_pending: set[int] = set()  # id(fragment) currently queued
_inflight = 0  # fragments popped but not yet snapshotted
_idle = threading.Condition(_lock)


def _snapshot_swallowing(frag) -> None:
    """Run one compaction; a failure is survivable (durability is
    WAL-carried, the next threshold retries) but never silent — a
    persistently failing disk must not starve compaction invisibly."""
    try:
        frag.snapshot()
    except Exception as e:
        import sys

        print(f"snapshot queue: compaction of {frag.path!r} failed "
              f"({e!r}); WAL keeps growing until a retry succeeds",
              file=sys.stderr)


def _worker() -> None:
    global _inflight
    while True:
        frag = _queue.get()
        try:
            _snapshot_swallowing(frag)
        finally:
            with _lock:
                _pending.discard(id(frag))
                _inflight -= 1
                _idle.notify_all()
            _queue.task_done()


def _ensure_workers() -> None:
    global _queue
    if _queue is not None:
        return
    with _lock:
        if _queue is not None:
            return
        _queue = queue.Queue(maxsize=QUEUE_DEPTH)
        for i in range(N_WORKERS):
            t = threading.Thread(target=_worker, daemon=True,
                                 name=f"snapshot-worker-{i}")
            t.start()
            _workers.append(t)


def enqueue(frag) -> None:
    """Queue a fragment for background compaction; de-duplicates (a
    fragment already queued is skipped) and degrades to inline when the
    queue is full."""
    global _inflight
    _ensure_workers()
    with _lock:
        if id(frag) in _pending:
            return
        _pending.add(id(frag))
        _inflight += 1
    try:
        _queue.put_nowait(frag)
    except queue.Full:
        # backpressure: the overflowing write pays for one compaction
        # inline rather than queueing unbounded work.  Failures are
        # swallowed exactly like the worker path — the triggering write
        # already succeeded durably (bit applied + WAL appended)
        try:
            _snapshot_swallowing(frag)
        finally:
            with _lock:
                _pending.discard(id(frag))
                _inflight -= 1
                _idle.notify_all()


def drain(timeout: float | None = 30.0) -> bool:
    """Block until every queued snapshot has completed.  Returns False
    on timeout; ``timeout=None`` blocks indefinitely."""
    if _queue is None:
        return True
    import time

    deadline = None if timeout is None else time.monotonic() + timeout
    with _idle:
        while _inflight > 0:
            if deadline is None:
                _idle.wait(timeout=1.0)  # re-check; no deadline to miss
                continue
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            _idle.wait(timeout=remaining)
    return True


def pending_count() -> int:
    with _lock:
        return len(_pending)
