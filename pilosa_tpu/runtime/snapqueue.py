"""Background snapshot queue: writes never stall on compaction.

The reference queues fragment snapshots on a 100-deep channel drained by
2 workers (holder.go:163, fragment.go:187-208) so a write that trips the
opN threshold enqueues the compaction and returns.  Same design here,
process-wide (one holder per process in practice, like the residency
manager): ``enqueue(frag)`` marks the fragment pending and hands it to a
worker; a full queue degrades to an inline snapshot (bounded memory, the
write that overflows pays the cost); ``drain()`` blocks until the queue
is empty — holder close and tests use it as a barrier.

Durability does not depend on the queue at all: every mutation is in the
WAL until ``snapshot()`` itself truncates it, so a crash at ANY point
before/during/after the background compaction replays losslessly (the
same guarantee as the reference's in-file op-log, roaring.go:1612).
"""

from __future__ import annotations

import queue
import threading

from pilosa_tpu import logger as _logger

QUEUE_DEPTH = 100
N_WORKERS = 2

_queue: queue.Queue | None = None
_workers: list[threading.Thread] = []
_lock = threading.Lock()
_pending: set[int] = set()  # id(fragment) currently queued
_inflight = 0  # fragments popped but not yet snapshotted
_idle = threading.Condition(_lock)

#: Queue health counters, process-wide like the queue itself.  Exposed
#: at every server's /metrics (handler appends ``prometheus_lines()``)
#: so compaction starvation is alert-able, not stderr-only (the
#: reference surfaces the analogous state via expvar, stats/stats.go:84).
_counters = {
    "snapshot_failures": 0,   # compactions that raised (worker or inline)
    "snapshot_completed": 0,  # compactions that succeeded
    "drain_timeouts": 0,      # drain() callers that gave up waiting
    "queue_overflows": 0,     # enqueues that degraded to inline
}

#: Failures must never be silent even with a NOP server logger, so the
#: module default is a real stderr logger; a server may swap in its own.
log: _logger.Logger = _logger.StandardLogger()


def bump(name: str, value: int = 1) -> None:
    with _lock:
        _counters[name] += value


def counters() -> dict:
    with _lock:
        return dict(_counters)


def prometheus_lines() -> str:
    """Counters as Prometheus 0.0.4 text, for appending to /metrics."""
    out = []
    for name, v in sorted(counters().items()):
        m = f"pilosa_snapqueue_{name}_total"
        out.append(f"# TYPE {m} counter")
        out.append(f"{m} {v}")
    return "\n".join(out) + "\n"


def _snapshot_swallowing(frag) -> None:
    """Run one compaction; a failure is survivable (durability is
    WAL-carried, the next threshold retries) but never silent — a
    persistently failing disk must not starve compaction invisibly."""
    try:
        frag.snapshot()
        bump("snapshot_completed")
    except Exception as e:
        bump("snapshot_failures")
        log.printf("snapshot queue: compaction of %r failed (%r); "
                   "WAL keeps growing until a retry succeeds",
                   frag.path, e)


def _worker() -> None:
    global _inflight
    while True:
        frag = _queue.get()
        try:
            _snapshot_swallowing(frag)
        finally:
            with _lock:
                _pending.discard(id(frag))
                _inflight -= 1
                _idle.notify_all()
            _queue.task_done()


def _ensure_workers() -> None:
    global _queue
    if _queue is not None:
        return
    with _lock:
        if _queue is not None:
            return
        _queue = queue.Queue(maxsize=QUEUE_DEPTH)
        for i in range(N_WORKERS):
            t = threading.Thread(target=_worker, daemon=True,
                                 name=f"snapshot-worker-{i}")
            t.start()
            _workers.append(t)


def enqueue(frag) -> None:
    """Queue a fragment for background compaction; de-duplicates (a
    fragment already queued is skipped) and degrades to inline when the
    queue is full."""
    global _inflight
    _ensure_workers()
    with _lock:
        if id(frag) in _pending:
            return
        _pending.add(id(frag))
        _inflight += 1
    try:
        _queue.put_nowait(frag)
    except queue.Full:
        # backpressure: the overflowing write pays for one compaction
        # inline rather than queueing unbounded work.  Failures are
        # swallowed exactly like the worker path — the triggering write
        # already succeeded durably (bit applied + WAL appended)
        bump("queue_overflows")
        try:
            _snapshot_swallowing(frag)
        finally:
            with _lock:
                _pending.discard(id(frag))
                _inflight -= 1
                _idle.notify_all()


def drain(timeout: float | None = 30.0) -> bool:
    """Block until every queued snapshot has completed.  Returns False
    on timeout; ``timeout=None`` blocks indefinitely."""
    if _queue is None:
        return True
    import time

    deadline = None if timeout is None else time.monotonic() + timeout
    with _idle:
        while _inflight > 0:
            if deadline is None:
                _idle.wait(timeout=1.0)  # re-check; no deadline to miss
                continue
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                # _idle wraps _lock (non-reentrant) and we're inside
                # `with _idle:` — bump() would self-deadlock here
                _counters["drain_timeouts"] += 1
                return False
            _idle.wait(timeout=remaining)
    return True


def pending_count() -> int:
    with _lock:
        return len(_pending)
