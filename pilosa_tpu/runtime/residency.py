"""Unified device-memory residency manager.

Every cached device tensor — per-fragment row matrices and BSI planes
(`Fragment._device_cache`), cross-shard row stacks and concatenated
matrix stacks (`Field._row_stack_cache` / `_matrix_stack_cache`) — is
registered here under ONE process-wide byte budget with LRU eviction
across owners.  Before this layer each cache byte-budgeted itself, so
mixed workloads could hold a field's matrices on device several times
over without any cap seeing the total (the SURVEY.md §7 risk-register
item: the "fragment heap manager" half of the C++ PJRT host runtime —
host-side accounting here; the tensors themselves live in HBM and are
freed by dropping the owning cache reference, which releases the jax
buffer once no computation holds it).

Reference analog: the mmap budget caps of syswrap (syswrap/os.go:41,
syswrap/mmap.go:27) — a global guard over per-object storage residency.

Eviction only drops CACHE references.  Owners rebuild evicted entries
from host state on the next query (every registered tensor is a cache
of host-resident data by construction), so eviction can never lose
data — only warmth.
"""

from __future__ import annotations

import os
import threading


def live(dev) -> bool:
    """A cached device array can outlive its backend (jax
    clear_backends — e.g. __graft_entry__'s virtual-mesh reset); a
    deleted array must read as a cache miss, not a RuntimeError.
    Shared by every device-tensor cache this manager accounts."""
    try:
        return not dev.is_deleted()
    except Exception:
        return True


def _operator_sized() -> bool:
    return bool(os.environ.get("PILOSA_TPU_DEVICE_BUDGET_BYTES"))


def _default_budget() -> int:
    env = os.environ.get("PILOSA_TPU_DEVICE_BUDGET_BYTES")
    if env:
        return int(env)
    # Probe the backend for real memory limits (works on TPU); fall
    # back to a conservative figure that keeps CPU test runs light.
    try:
        import jax

        stats = jax.devices()[0].memory_stats()
        if stats and "bytes_limit" in stats:
            # leave headroom for executables, collectives and live
            # intermediates; caches may take at most 60%
            return int(stats["bytes_limit"] * 0.6) * len(jax.devices())
    except Exception:
        pass
    return 2 << 30


class ResidencyManager:
    """LRU accounting of cached device tensors across all owners.

    Owners call ``admit(cache_dict, key, nbytes)`` AFTER inserting the
    entry into their own dict; the manager may synchronously evict
    other entries (possibly from other owners) by deleting them from
    their owner dicts.  Owners must therefore treat a missing key as a
    cold cache and rebuild — which they already do, since generation
    mismatches produce exactly the same miss."""

    def __init__(self, budget_bytes: int | None = None):
        self.budget = budget_bytes or _default_budget()
        # True when the budget was chosen by an operator (explicit
        # constructor arg or env var) rather than probed; cache-entry
        # caps only relax for deliberately-sized deployments
        self.operator_sized = budget_bytes is not None or _operator_sized()
        self._lock = threading.Lock()
        # (owner dict id, key) -> (owner dict, key, nbytes, kind,
        # devices); dict preserves insertion order = LRU order
        # (move-to-end on touch)
        self._entries: dict[tuple, tuple] = {}
        self.total = 0
        # sum of per-entry ceil(nbytes / devices): what the most-loaded
        # single device holds when entries shard over the [mesh] plan
        self._per_device = 0
        # bytes by representation kind ("dense" tensors vs the
        # roaring-on-TPU "compressed" container pools) — the
        # /debug/devices compressed-vs-dense split, and the number
        # that shows one chip admitting several times more index when
        # sparse fragments ride the compressed layout
        self._by_kind: dict[str, int] = {}
        self.evictions = 0
        self.admits = 0
        # max SETTLED bytes (post-eviction; the mid-admit transient
        # spike is excluded — see the update site in admit())
        self.high_water = 0

    @staticmethod
    def _id(cache: dict, key) -> tuple:
        return (id(cache), key)

    def admit(self, cache: dict, key, nbytes: int,
              kind: str = "dense", devices: int = 1) -> None:
        """Track an entry just inserted into ``cache`` under ``key``;
        evict least-recently-used entries (from any owner) until the
        total fits the budget.  The entry being admitted is never its
        own victim, so the total is bounded by max(budget, largest
        single entry) even when individual entries exceed the whole
        budget — an unconditional reclaim, like the reference's global
        syswrap caps (syswrap/os.go:41).  ``kind`` tags the bytes as
        "dense" tensors or roaring "compressed" container pools, so
        the stats() split reports REAL compressed residency.
        ``devices`` is how many mesh devices the entry's bytes spread
        over under the [mesh] shard plan (parallel/meshexec.py) —
        stats() reports the resulting worst-per-device residency so
        an operator sizes HBM against what ONE chip actually holds."""
        eid = self._id(cache, key)
        with self._lock:
            old = self._entries.pop(eid, None)
            if old is not None:
                self.total -= old[2]
                self._by_kind[old[3]] = \
                    self._by_kind.get(old[3], 0) - old[2]
                self._per_device -= -(-old[2] // old[4])
            self._entries[eid] = (cache, key, nbytes, kind,
                                  max(1, devices))
            self.total += nbytes
            self._per_device += -(-nbytes // max(1, devices))
            self._by_kind[kind] = self._by_kind.get(kind, 0) + nbytes
            self.admits += 1
            while self.total > self.budget and len(self._entries) > 1:
                victim_id = next(iter(self._entries))
                if victim_id == eid:
                    # never evict the entry being admitted
                    self._entries[eid] = self._entries.pop(eid)
                    continue
                (vcache, vkey, vbytes, vkind,
                 vdev) = self._entries.pop(victim_id)
                self.total -= vbytes
                self._per_device -= -(-vbytes // vdev)
                self._by_kind[vkind] = \
                    self._by_kind.get(vkind, 0) - vbytes
                self.evictions += 1
                vcache.pop(vkey, None)
            # high-water marks the SETTLED residency level (the number
            # an operator sizes the budget against), so it updates
            # after eviction reclaims — the transient mid-admit spike
            # is an accounting artifact, not held bytes
            if self.total > self.high_water:
                self.high_water = self.total

    def touch(self, cache: dict, key) -> None:
        """Mark an entry recently used (cache hit)."""
        eid = self._id(cache, key)
        with self._lock:
            e = self._entries.pop(eid, None)
            if e is not None:
                self._entries[eid] = e

    def forget(self, cache: dict, key) -> None:
        """Stop tracking an entry the owner removed itself (overwrite,
        invalidation, fragment delete)."""
        eid = self._id(cache, key)
        with self._lock:
            e = self._entries.pop(eid, None)
            if e is not None:
                self.total -= e[2]
                self._per_device -= -(-e[2] // e[4])
                self._by_kind[e[3]] = self._by_kind.get(e[3], 0) - e[2]

    def evict_all(self) -> int:
        """Drop EVERY tracked cache entry (device-OOM recovery: the
        executor's RESOURCE_EXHAUSTED retry path drains all cached
        device tensors before re-launching).  Owners rebuild from host
        state on the next touch — eviction loses warmth, never data.
        Returns the number of entries evicted."""
        with self._lock:
            victims = list(self._entries.values())
            self._entries.clear()
            self.total = 0
            self._per_device = 0
            self._by_kind.clear()
            self.evictions += len(victims)
            # owner-dict pops stay under the lock (the admit() victim
            # discipline): released, a concurrent admit could insert a
            # fresh entry for the same key between our snapshot and
            # pop — we would drop ITS tensor while _entries still
            # tracks it, permanently skewing the byte accounting
            for vcache, vkey, _vbytes, _vkind, _vdev in victims:
                vcache.pop(vkey, None)
        return len(victims)

    def stats(self) -> dict:
        with self._lock:
            return {"budget": self.budget, "total": self.total,
                    "entries": len(self._entries),
                    "evictions": self.evictions,
                    "admits": self.admits,
                    "high_water": self.high_water,
                    # what one chip holds when stacks shard over the
                    # [mesh] plan: sum of ceil(bytes / devices) — equal
                    # to total with the mesh off, total/axis when every
                    # entry shards (the /debug/devices + /debug/mesh
                    # per-device residency line)
                    "per_device": self._per_device,
                    # compressed-vs-dense residency split (the
                    # roaring-on-TPU capacity story; /debug/devices)
                    "kinds": {k: v for k, v in self._by_kind.items()
                              if v}}

    def top_entries(self, n: int = 20) -> list[dict]:
        """Largest tracked device/host cache entries, for the heap
        profile endpoint — on a framework whose risk register is memory
        layout, 'which stacks hold the bytes' is the first question a
        10B-scale operator asks."""
        with self._lock:
            entries = sorted(self._entries.values(), key=lambda e: -e[2])[:n]
        return [{"key": repr(key)[:160], "bytes": nbytes,
                 "kind": kind, "devices": devices}
                for _, key, nbytes, kind, devices in entries]


_global: ResidencyManager | None = None
_global_lock = threading.Lock()


def manager() -> ResidencyManager:
    """The process-wide manager (one budget per process, like the
    reference's global syswrap caps)."""
    global _global
    with _global_lock:
        if _global is None:
            _global = ResidencyManager()
        return _global


def reset(budget_bytes: int | None = None) -> ResidencyManager:
    """Replace the global manager (tests; budget reconfiguration)."""
    global _global
    with _global_lock:
        _global = ResidencyManager(budget_bytes)
        return _global
