"""Tiered device-memory residency: HBM in front of a host-RAM tier
(with an optional disk tier behind it), async promotion, and graceful
degradation under memory pressure.

Every cached device tensor — per-fragment row matrices and BSI planes
(`Fragment._device_cache`), cross-shard row stacks, concatenated
matrix stacks and compressed container pools (`Field._row_stack_cache`
/ `_matrix_stack_cache`) — is registered here under ONE process-wide
HBM byte budget with LRU eviction across owners (the SURVEY.md §7
"fragment heap manager"; reference analog: the global syswrap mmap
caps, syswrap/os.go:41, a budget over per-object storage residency).

What changed from the flat manager (the ROADMAP item-4 "working set ≫
device memory" gap): a budget miss used to mean the owner re-assembled
the stack from fragment state and re-uploaded it INLINE on the query
path, and a working set larger than HBM degenerated into an eviction
thrash loop with no backpressure.  Now:

- **Eviction demotes instead of drops.**  Owners hand ``admit()`` the
  assembled HOST bytes (``host=``) plus a rebuild closure
  (``promote=``); those bytes live in a host-RAM tier (LRU under its
  own ``[residency] host-budget-bytes``), so an HBM eviction only
  drops the device reference — the expensive host-side assembly
  (fragment locks, concatenation, delta merges) is never repeated
  while the host entry stays valid.  Host-tier overflow spills
  ndarray payloads to the optional disk tier (``disk-path``) or drops.
- **Misses enqueue an async promotion.**  A query that misses HBM but
  hits the host tier submits the entry to a bounded promotion worker
  pool (single-flight per key, each job admitted under the admission
  controller's ``internal`` class) and waits a BOUNDED slice of its
  deadline; if the promotion lands in time the query reads the
  promoted device entry, otherwise it takes the **host-compute
  fallback** — it evaluates over the host bytes directly (bit-exact;
  the promotion continues in the background for the next query).
- **Pressure sheds lowest-value work first.**  A full promotion queue
  drops queued PREFETCH jobs before refusing a demand promotion; a
  refused demand promotion is an immediate host fallback, never an
  unbounded stall; admission-saturated workers shed the same way.
- **RESOURCE_EXHAUSTED feeds back into the budget.**
  :func:`run_with_oom_retry` (the shared evict-and-retry wrapper for
  every fused dispatch site) shrinks the HBM budget on each recovered
  OOM so the tier demotes harder instead of re-hitting the wall.

The predictive prefetcher (``runtime/prefetch.py``) promotes
host-tier entries ahead of demand, ranked by the flight recorder's
access statistics (``observe.access_stats``).

``?notiers=1`` (ExecOptions.tiers=False -> :class:`no_tiers`) routes
the exact pre-tier behavior: misses rebuild inline, evictions drop.
Results are byte-identical either way — the tier only moves WHERE
bytes live and WHEN they transfer, never what they contain.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque

import numpy as np

from pilosa_tpu import lockcheck as _lockcheck
from pilosa_tpu.serve import tenant as _tenantmod
from pilosa_tpu.serve.deadline import tls_scope as _tls_scope


def live(dev) -> bool:
    """A cached device array can outlive its backend (jax
    clear_backends — e.g. __graft_entry__'s virtual-mesh reset); a
    deleted array must read as a cache miss, not a RuntimeError.
    Shared by every device-tensor cache this manager accounts."""
    try:
        return not dev.is_deleted()
    except Exception:
        return True


def _operator_sized() -> bool:
    return bool(os.environ.get("PILOSA_TPU_DEVICE_BUDGET_BYTES"))


def _default_budget() -> int:
    env = os.environ.get("PILOSA_TPU_DEVICE_BUDGET_BYTES")
    if env:
        return int(env)
    # Probe the backend for real memory limits (works on TPU); fall
    # back to a conservative figure that keeps CPU test runs light.
    try:
        import jax

        stats = jax.devices()[0].memory_stats()
        if stats and "bytes_limit" in stats:
            # leave headroom for executables, collectives and live
            # intermediates; caches may take at most 60%
            return int(stats["bytes_limit"] * 0.6) * len(jax.devices())
    except Exception:
        pass
    return 2 << 30


#: The HBM budget never feedback-shrinks below this floor — a storm of
#: RESOURCE_EXHAUSTED retries must converge on "small but serving",
#: not zero.
MIN_BUDGET_BYTES = 16 << 20


# --------------------------------------------------------------------
# [residency] runtime config (process-wide, like [containers]/[mesh])
# --------------------------------------------------------------------


class TierRuntimeConfig:
    """The process-wide [residency] knobs.  ``host_budget_bytes`` is
    the host-RAM tier cap (0 disables tiering entirely — the exact
    pre-tier manager); ``promote_wait_ms`` bounds how long a demand
    miss parks on its async promotion before taking the host-compute
    fallback (further capped by the request's own deadline)."""

    __slots__ = ("host_budget_bytes", "disk_path", "disk_budget_bytes",
                 "promote_workers", "promote_queue", "promote_wait_ms",
                 "prefetch", "prefetch_interval")

    def __init__(self) -> None:
        self.host_budget_bytes = 1 << 30
        self.disk_path = ""  # empty = no disk tier
        self.disk_budget_bytes = 4 << 30
        self.promote_workers = 2
        self.promote_queue = 64
        self.promote_wait_ms = 50.0
        self.prefetch = True
        self.prefetch_interval = 0.25


_cfg = TierRuntimeConfig()
_cfg_lock = threading.Lock()
_baseline: tuple | None = None
_refs = 0


def config() -> TierRuntimeConfig:
    return _cfg


def configure(host_budget_bytes: int | None = None,
              disk_path: str | None = None,
              disk_budget_bytes: int | None = None,
              promote_workers: int | None = None,
              promote_queue: int | None = None,
              promote_wait_ms: float | None = None,
              prefetch: bool | None = None,
              prefetch_interval: float | None = None) -> TierRuntimeConfig:
    """Apply [residency] config in place — only explicit values land,
    so a second in-process server cannot wipe the first's settings
    with defaults (the containers.configure contract)."""
    with _cfg_lock:
        if host_budget_bytes is not None:
            _cfg.host_budget_bytes = int(host_budget_bytes)
        if disk_path is not None:
            _cfg.disk_path = str(disk_path)
        if disk_budget_bytes is not None:
            _cfg.disk_budget_bytes = int(disk_budget_bytes)
        if promote_workers is not None:
            _cfg.promote_workers = max(1, int(promote_workers))
        if promote_queue is not None:
            _cfg.promote_queue = max(1, int(promote_queue))
        if promote_wait_ms is not None:
            _cfg.promote_wait_ms = float(promote_wait_ms)
        if prefetch is not None:
            _cfg.prefetch = bool(prefetch)
        if prefetch_interval is not None:
            _cfg.prefetch_interval = float(prefetch_interval)
    return _cfg


def retain() -> None:
    """Take a server reference; the FIRST holder snapshots the
    pre-server baseline config (restore composes correctly under any
    close order — the PR-6 [ingest] lesson, pilosa-lint P5)."""
    global _refs, _baseline
    with _cfg_lock:
        if _refs == 0 and _baseline is None:
            _baseline = (_cfg.host_budget_bytes, _cfg.disk_path,
                         _cfg.disk_budget_bytes, _cfg.promote_workers,
                         _cfg.promote_queue, _cfg.promote_wait_ms,
                         _cfg.prefetch, _cfg.prefetch_interval)
        _refs += 1


def release() -> None:
    """Drop a server reference; the LAST holder restores the captured
    baseline and stops the shared promotion workers."""
    global _refs, _baseline
    stop = False
    with _cfg_lock:
        if _refs > 0:
            _refs -= 1
        if _refs == 0 and _baseline is not None:
            (_cfg.host_budget_bytes, _cfg.disk_path,
             _cfg.disk_budget_bytes, _cfg.promote_workers,
             _cfg.promote_queue, _cfg.promote_wait_ms,
             _cfg.prefetch, _cfg.prefetch_interval) = _baseline
            _baseline = None
            stop = True
    if stop:
        promoter().stop()


# --------------------------------------------------------------------
# per-request escape (?notiers=1)
# --------------------------------------------------------------------

_tls = threading.local()  # .notiers: True inside a no_tiers scope


class no_tiers(_tls_scope):
    """Install the ?notiers=1 escape for a scope: host-tier lookups
    miss, evictions drop instead of demoting, and admits register no
    host payload — the exact pre-tier manager behavior.  Re-entrant;
    the executor installs it for the whole execution and re-installs
    it on map workers alongside the flight record."""

    __slots__ = ()

    def __init__(self, on: bool = True):
        super().__init__(_tls, "notiers", on)


def tiers_off_scope() -> bool:
    """True while this thread runs under a ``no_tiers`` scope."""
    return bool(getattr(_tls, "notiers", False))


def tiers_enabled() -> bool:
    """Tiering in force for THIS thread right now: the [residency]
    host budget is nonzero and no ?notiers scope is installed."""
    return _cfg.host_budget_bytes > 0 and not tiers_off_scope()


# --------------------------------------------------------------------
# host/disk tier entries
# --------------------------------------------------------------------


def _payload_nbytes(payload) -> int:
    """Host bytes held by one tier payload: an ndarray, or a tuple
    whose ndarray leaves count (non-array metadata is negligible)."""
    if isinstance(payload, np.ndarray):
        return payload.nbytes
    if isinstance(payload, (tuple, list)):
        return sum(p.nbytes for p in payload
                   if isinstance(p, np.ndarray))
    return 0


def _payload_arrays_only(payload) -> bool:
    """True when the payload is spillable to disk: a bare ndarray or a
    flat tuple of ndarrays (container-leaf payloads carry host-object
    metadata and stay RAM-only)."""
    if isinstance(payload, np.ndarray):
        return True
    return (isinstance(payload, (tuple, list)) and len(payload) > 0
            and all(isinstance(p, np.ndarray) for p in payload))


class HostEntry:
    """One demotable/demoted entry's host-side half: the assembled
    bytes, the validity token, and the rebuild closure that turns the
    bytes back into an owner-cache entry (placement included)."""

    __slots__ = ("cache", "key", "token", "payload", "promote",
                 "fallback", "nbytes", "kind", "devices", "spilled",
                 "tenant", "kind_detail")

    def __init__(self, cache: dict, key, token, payload, promote,
                 nbytes: int, kind: str, devices: int, fallback=None,
                 tenant: str | None = None, kind_detail=None):
        self.cache = cache
        self.key = key
        self.token = token
        self.payload = payload  # None while spilled to disk
        self.promote = promote
        # host-compute adapter: payload -> the value a deadline-bounded
        # caller consumes WITHOUT device placement (None: the payload
        # itself already is that value, e.g. a plain host stack)
        self.fallback = fallback
        self.nbytes = nbytes
        self.kind = kind
        self.devices = devices
        self.spilled: str | None = None  # .npz path when on disk
        # the tenant whose query assembled these bytes ([tenants]
        # isolation; None while off) — host-tier byte attribution
        self.tenant = tenant
        # per-kind byte breakout ({"array": n, "run": n}) restored on
        # re-promotion so stats()["kinds"] survives a demote cycle
        self.kind_detail = kind_detail

    def host_value(self):
        """The host-compute fallback value for this entry."""
        if self.fallback is not None:
            return self.fallback(self.payload)
        return self.payload

    @property
    def eid(self) -> tuple:
        return (id(self.cache), self.key)


class ResidencyManager:
    """Tiered LRU accounting of cached device tensors across all
    owners.

    Owners call ``admit(cache_dict, key, nbytes, ...)`` AFTER inserting
    the entry into their own dict; the manager may synchronously evict
    other entries (possibly from other owners) by deleting them from
    their owner dicts — demoting their host bytes into the host tier
    when the owner supplied them.  Owners must therefore treat a
    missing key as a cold cache and consult ``host_lookup`` before
    rebuilding — which composes with the existing discipline, since
    generation mismatches produce exactly the same miss."""

    def __init__(self, budget_bytes: int | None = None):
        self.budget = budget_bytes or _default_budget()
        self.budget_initial = self.budget
        # True when the budget was chosen by an operator (explicit
        # constructor arg or env var) rather than probed; cache-entry
        # caps only relax for deliberately-sized deployments
        self.operator_sized = budget_bytes is not None or _operator_sized()
        self._lock = _lockcheck.lock("residency")
        # (owner dict id, key) -> (owner dict, key, nbytes, kind,
        # devices); dict preserves insertion order = LRU order
        # (move-to-end on touch)
        self._entries: dict[tuple, tuple] = {}
        self.total = 0
        # sum of per-entry ceil(nbytes / devices): what the most-loaded
        # single device holds when entries shard over the [mesh] plan
        self._per_device = 0
        # bytes by representation kind ("dense" tensors vs the
        # roaring-on-TPU "compressed" container pools) — the
        # /debug/devices compressed-vs-dense split
        self._by_kind: dict[str, int] = {}
        # eid -> {"array": n, "run": n}: sub-kind byte breakout for
        # kinds-split container leaves, charged ADDITIVELY into
        # _by_kind ("compressed" stays the pool total)
        self._kind_detail: dict[tuple, dict] = {}
        self.evictions = 0
        self.admits = 0
        # max SETTLED bytes (post-eviction; the mid-admit transient
        # spike is excluded — see the update site in admit())
        self.high_water = 0
        # ---------------- host tier ----------------
        # eid -> HostEntry; insertion order = LRU
        self._host: dict[tuple, HostEntry] = {}
        self._host_bytes = 0
        # eid -> HostEntry whose payload lives in a .npz on disk
        self._disk: dict[tuple, HostEntry] = {}
        self._disk_bytes = 0
        self._spill_seq = 0
        # tier accounting (residency.tier.* gauges)
        self.demotions = 0       # HBM evictions that kept host bytes
        self.tier_hits = 0       # host_lookup served a valid entry
        self.tier_misses = 0     # host_lookup found nothing usable
        self.tier_spills = 0     # host-tier overflow pushed to disk
        self.tier_spill_drops = 0  # overflow with no disk tier: dropped
        self.disk_hits = 0       # disk payload reloaded into host tier
        self.fallbacks = 0       # queries served host-compute fallback
        self.oom_budget_shrinks = 0
        # eids whose resident entry was installed by the prefetcher
        # and not yet touched by a query (prefetch.useful accounting)
        self._prefetched: set[tuple] = set()
        self.prefetch_useful = 0
        # ---------------- per-tenant accounting ([tenants]) --------
        # tenant -> HBM bytes / host-tier bytes its stacks hold, and
        # the demotion PRESSURE charged to each tenant (evictions its
        # over-quota admissions forced onto its own entries).  Only
        # touched while the admitting thread carries a tenant scope.
        self._tenant_bytes: dict[str, int] = {}
        self._tenant_host_bytes: dict[str, int] = {}
        self._tenant_pressure: dict[str, int] = {}

    @staticmethod
    def _id(cache: dict, key) -> tuple:
        return (id(cache), key)

    # --------------------------------------------------- tenant hooks

    @staticmethod
    def _admitting_tenant(old_tenant: str | None) -> str | None:
        """The tenant this admission charges: the thread-local scope
        (the executor installs the request's id), inheriting the
        entry's previous owner when the admitting thread is anonymous
        (promotion workers, prefetch) — None while [tenants] is off."""
        if not _tenantmod.enabled():
            return None
        t = _tenantmod.current()
        if t is not None:
            # through resolve(): the individuation bound collapses
            # rotated unconfigured labels into the default tier
            return _tenantmod.resolve(t)
        return old_tenant or _tenantmod.DEFAULT_TENANT

    @staticmethod
    def _tenant_quota_bytes(t: str, budget: int) -> int:
        """The tenant's share of ``budget`` (0 = unenforced)."""
        pol = _tenantmod.policy()
        if pol is None:
            return 0
        return int(budget * pol.quota_for(t).residency_share)

    def _tenant_charge_locked(self, t: str | None, n: int) -> None:
        if t is not None:
            self._tenant_bytes[t] = self._tenant_bytes.get(t, 0) + n

    def _tenant_host_charge_locked(self, t: str | None, n: int) -> None:
        if t is not None:
            self._tenant_host_bytes[t] = \
                self._tenant_host_bytes.get(t, 0) + n

    def _kind_detail_drop_locked(self, eid: tuple) -> None:
        """Un-charge an entry's sub-kind byte breakout from
        ``_by_kind`` (eviction/forget/demote/overwrite)."""
        d = self._kind_detail.pop(eid, None)
        if d:
            for k, v in d.items():
                self._by_kind[k] = self._by_kind.get(k, 0) - v

    # ---------------------------------------------------------- admit

    def admit(self, cache: dict, key, nbytes: int,
              kind: str = "dense", devices: int = 1,
              token=None, host=None, promote=None, fallback=None,
              prefetched: bool = False, kind_detail=None) -> None:
        """Track an entry just inserted into ``cache`` under ``key``;
        evict least-recently-used entries (from any owner) until the
        total fits the budget.  The entry being admitted is never its
        own victim, so the total is bounded by max(budget, largest
        single entry) even when individual entries exceed the whole
        budget.

        ``kind`` tags the bytes ("dense" vs roaring "compressed");
        ``devices`` is the [mesh] spread for per-device accounting.
        ``token``+``host``+``promote`` opt the entry into the host
        tier: ``host`` is the assembled host payload, ``promote`` a
        closure rebuilding the owner-cache entry value from it
        (placement included) — with them, eviction DEMOTES (keeps the
        host bytes for async re-promotion) instead of dropping."""
        eid = self._id(cache, key)
        tiers = host is not None and promote is not None \
            and tiers_enabled()
        spill: list[HostEntry] = []
        with self._lock:
            old = self._entries.pop(eid, None)
            ten = self._admitting_tenant(
                old[5] if old is not None else None)
            if old is not None:
                self.total -= old[2]
                self._by_kind[old[3]] = \
                    self._by_kind.get(old[3], 0) - old[2]
                self._per_device -= -(-old[2] // old[4])
                self._tenant_charge_locked(old[5], -old[2])
            self._kind_detail_drop_locked(eid)
            self._entries[eid] = (cache, key, nbytes, kind,
                                  max(1, devices), ten)
            self.total += nbytes
            self._per_device += -(-nbytes // max(1, devices))
            self._by_kind[kind] = self._by_kind.get(kind, 0) + nbytes
            if kind_detail:
                # sub-kind breakout ("array"/"run" pool bytes inside a
                # "compressed" leaf) — additive, so the parent kind
                # remains the authoritative total
                self._kind_detail[eid] = dict(kind_detail)
                for k, v in kind_detail.items():
                    self._by_kind[k] = self._by_kind.get(k, 0) + v
            self._tenant_charge_locked(ten, nbytes)
            self.admits += 1
            if prefetched:
                self._prefetched.add(eid)
            else:
                self._prefetched.discard(eid)
            if tiers:
                # the host payload is registered ONCE, here, whether
                # the entry is resident or demoted — one accounting
                # site, one budget (a resident entry's host twin is
                # what makes its future demotion free)
                spill = self._host_put_locked(HostEntry(
                    cache, key, token, host, promote,
                    _payload_nbytes(host), kind, max(1, devices),
                    fallback=fallback, tenant=ten,
                    kind_detail=kind_detail))
            if ten is not None:
                # per-tenant HBM quota ([tenants] residency-share):
                # an over-quota tenant demotes its OWN coldest stacks,
                # never the fleet's zipfian head — the demotion
                # pressure is charged to the tenant that caused it
                tq = self._tenant_quota_bytes(ten, self.budget)
                while (tq > 0
                       and self._tenant_bytes.get(ten, 0) > tq):
                    vid = next((v for v, e in self._entries.items()
                                if e[5] == ten and v != eid), None)
                    if vid is None:
                        break
                    self._evict_one_locked(vid)
                    self._tenant_pressure[ten] = \
                        self._tenant_pressure.get(ten, 0) + 1
            while self.total > self.budget and len(self._entries) > 1:
                # prefer demoting a dense twin over a compressed
                # container pool: the dense stack re-promotes from its
                # host twin (or rebuilds from fragments), while the
                # pool is what the bitmap VM gathers from — losing it
                # forces the whole bucket back to the dense path.  The
                # scan is bounded so admit() stays O(1)-ish; past the
                # window the plain LRU head goes
                victim_id = next(
                    (vid for vid, e in itertools.islice(
                        self._entries.items(), 32)
                     if vid != eid and e[3] == "dense"),
                    None)
                if victim_id is None:
                    victim_id = next(iter(self._entries))
                if victim_id == eid:
                    # never evict the entry being admitted
                    self._entries[eid] = self._entries.pop(eid)
                    continue
                self._evict_one_locked(victim_id)
            # high-water marks the SETTLED residency level (the number
            # an operator sizes the budget against), so it updates
            # after eviction reclaims — the transient mid-admit spike
            # is an accounting artifact, not held bytes
            if self.total > self.high_water:
                self.high_water = self.total
        if spill:
            self._spill_victims(spill)

    def _evict_one_locked(self, victim_id: tuple) -> None:
        """Drop one HBM entry (owner-dict pop included), demoting —
        i.e. leaving its host-tier twin in place — when one exists."""
        (vcache, vkey, vbytes, vkind,
         vdev, vtenant) = self._entries.pop(victim_id)
        self.total -= vbytes
        self._per_device -= -(-vbytes // vdev)
        self._by_kind[vkind] = self._by_kind.get(vkind, 0) - vbytes
        self._kind_detail_drop_locked(victim_id)
        self._tenant_charge_locked(vtenant, -vbytes)
        self.evictions += 1
        self._prefetched.discard(victim_id)
        if victim_id in self._host or victim_id in self._disk:
            self.demotions += 1
        vcache.pop(vkey, None)

    # ------------------------------------------------------ host tier

    def _host_put_locked(self, ent: HostEntry) -> list[HostEntry]:
        """Insert/refresh one host-tier entry; returns the LRU-overflow
        victims DETACHED from the tier — the caller hands them to
        :meth:`_spill_victims` AFTER releasing the lock (file IO must
        not serialize every admit; same discipline as the read side in
        host_lookup)."""
        eid = ent.eid
        old = self._host.pop(eid, None)
        if old is not None:
            self._host_bytes -= old.nbytes
            self._tenant_host_charge_locked(old.tenant, -old.nbytes)
        self._drop_disk_locked(eid)
        self._host[eid] = ent
        self._host_bytes += ent.nbytes
        self._tenant_host_charge_locked(ent.tenant, ent.nbytes)
        victims: list[HostEntry] = []
        if ent.tenant is not None:
            # per-tenant host-tier quota (residency-share of the host
            # budget): an over-quota tenant's own oldest host entries
            # overflow first — the HBM rule, applied to the tier
            tq = self._tenant_quota_bytes(ent.tenant,
                                          _cfg.host_budget_bytes)
            while (tq > 0
                   and self._tenant_host_bytes.get(ent.tenant, 0) > tq):
                vid = next((v for v, e in self._host.items()
                            if e.tenant == ent.tenant and v != eid),
                           None)
                if vid is None:
                    break
                v = self._host.pop(vid)
                self._host_bytes -= v.nbytes
                self._tenant_host_charge_locked(v.tenant, -v.nbytes)
                victims.append(v)
        while (self._host_bytes > _cfg.host_budget_bytes
               and len(self._host) > 1):
            vid = next(iter(self._host))
            if vid == eid:
                self._host[eid] = self._host.pop(eid)
                continue
            v = self._host.pop(vid)
            self._host_bytes -= v.nbytes
            self._tenant_host_charge_locked(v.tenant, -v.nbytes)
            victims.append(v)
        return victims

    def _spill_victims(self, victims: list[HostEntry]) -> None:
        """Host-tier overflow handling, OUTSIDE the manager lock:
        spill pure-array payloads to the disk tier (when configured)
        or drop.  The spilled record is a FRESH HostEntry — the
        evicted one may still be held by demand waiters and queued
        promotion jobs, whose host-compute fallback contract requires
        its payload to stay intact."""
        for v in victims:
            if not (_cfg.disk_path
                    and _payload_arrays_only(v.payload)):
                with self._lock:
                    self.tier_spill_drops += 1
                continue
            with self._lock:
                path = self._spill_path_locked()
            try:
                arrs = ([v.payload] if isinstance(v.payload, np.ndarray)
                        else list(v.payload))
                np.savez(path, *arrs)
            except OSError:
                with self._lock:
                    self.tier_spill_drops += 1
                continue
            d = HostEntry(v.cache, v.key, v.token, None, v.promote,
                          v.nbytes, v.kind, v.devices,
                          fallback=v.fallback, tenant=v.tenant,
                          kind_detail=v.kind_detail)
            d.spilled = path
            with self._lock:
                eid = v.eid
                if eid in self._host or eid in self._disk:
                    # a fresh admit re-entered while we wrote: our
                    # spill is stale — discard it, keep the live entry
                    stale = path
                else:
                    stale = None
                    self._disk[eid] = d
                    self._disk_bytes += d.nbytes
                    self.tier_spills += 1
                    while (self._disk_bytes > _cfg.disk_budget_bytes
                           and len(self._disk) > 1):
                        self._drop_disk_locked(next(iter(self._disk)),
                                               count_drop=True)
            if stale is not None:
                try:
                    os.remove(stale)
                except OSError:
                    pass

    def _spill_path_locked(self) -> str:
        self._spill_seq += 1
        os.makedirs(_cfg.disk_path, exist_ok=True)
        return os.path.join(_cfg.disk_path,
                            f"spill-{os.getpid()}-{self._spill_seq}.npz")

    def _drop_disk_locked(self, eid: tuple,
                          count_drop: bool = False) -> None:
        v = self._disk.pop(eid, None)
        if v is None:
            return
        self._disk_bytes -= v.nbytes
        if count_drop:
            self.tier_spill_drops += 1
        if v.spilled:
            try:
                os.remove(v.spilled)
            except OSError:
                pass

    def host_lookup(self, cache: dict, key, token) -> HostEntry | None:
        """The tier consult on an owner-cache miss: a HostEntry whose
        token still matches (LRU-touched), or None.  A stale entry is
        dropped on sight.  Disk-tier hits reload into the host tier
        first (one np.load — cheaper than re-assembling from fragment
        locks, which is the point of the tier)."""
        if not tiers_enabled():
            return None
        eid = self._id(cache, key)
        loaded = None
        with self._lock:
            e = self._host.get(eid)
            if e is None and eid in self._disk:
                loaded = self._disk[eid]
        if loaded is not None:
            # np.load OUTSIDE the lock (file IO must not serialize
            # every admit); a racing drop just wastes one read
            payload = self._load_spill(loaded)
            spill: list[HostEntry] = []
            with self._lock:
                if payload is not None and self._disk.get(eid) is loaded:
                    self._drop_disk_locked(eid)
                    # a FRESH entry: the disk record may be referenced
                    # elsewhere, and reload must never mutate a shared
                    # object (the spill-side rule, mirrored)
                    fresh = HostEntry(loaded.cache, loaded.key,
                                      loaded.token, payload,
                                      loaded.promote, loaded.nbytes,
                                      loaded.kind, loaded.devices,
                                      fallback=loaded.fallback,
                                      tenant=loaded.tenant,
                                      kind_detail=loaded.kind_detail)
                    spill = self._host_put_locked(fresh)
                    self.disk_hits += 1
            if spill:
                self._spill_victims(spill)
        with self._lock:
            e = self._host.get(eid)
            if e is None:
                self.tier_misses += 1
                return None
            if e.token != token:
                self._host.pop(eid, None)
                self._host_bytes -= e.nbytes
                self._tenant_host_charge_locked(e.tenant, -e.nbytes)
                self.tier_misses += 1
                return None
            self._host[eid] = self._host.pop(eid)  # LRU touch
            self.tier_hits += 1
            return e

    @staticmethod
    def _load_spill(ent: HostEntry):
        try:
            with np.load(ent.spilled) as z:
                arrs = [z[k] for k in z.files]
        except (OSError, ValueError):
            return None
        return arrs[0] if len(arrs) == 1 else tuple(arrs)

    def note_fallback(self) -> None:
        """One query served over host bytes (the deadline-bounded
        host-compute fallback path)."""
        with self._lock:
            self.fallbacks += 1

    # ------------------------------------------------------ lifecycle

    def touch(self, cache: dict, key) -> None:
        """Mark an entry recently used (cache hit)."""
        eid = self._id(cache, key)
        with self._lock:
            e = self._entries.pop(eid, None)
            if e is not None:
                self._entries[eid] = e
                if eid in self._prefetched:
                    # a query read an entry the prefetcher promoted:
                    # the prediction was useful, count it once
                    self._prefetched.discard(eid)
                    self.prefetch_useful += 1

    def forget(self, cache: dict, key) -> None:
        """Stop tracking an entry the owner removed itself (overwrite,
        invalidation, fragment delete) — host/disk twins drop too (the
        content is stale by definition)."""
        eid = self._id(cache, key)
        with self._lock:
            e = self._entries.pop(eid, None)
            self._prefetched.discard(eid)
            if e is not None:
                self.total -= e[2]
                self._per_device -= -(-e[2] // e[4])
                self._by_kind[e[3]] = self._by_kind.get(e[3], 0) - e[2]
                self._kind_detail_drop_locked(eid)
                self._tenant_charge_locked(e[5], -e[2])
            h = self._host.pop(eid, None)
            if h is not None:
                self._host_bytes -= h.nbytes
                self._tenant_host_charge_locked(h.tenant, -h.nbytes)
            self._drop_disk_locked(eid)

    def demote(self, cache: dict, key) -> None:
        """Owner-side demotion (cache-entry-cap eviction): stop HBM
        accounting but KEEP the host/disk twin — the entry is still
        valid, merely cold.  With tiering off this is exactly
        forget()."""
        if not tiers_enabled():
            self.forget(cache, key)
            return
        eid = self._id(cache, key)
        with self._lock:
            e = self._entries.pop(eid, None)
            self._prefetched.discard(eid)
            if e is not None:
                self.total -= e[2]
                self._per_device -= -(-e[2] // e[4])
                self._by_kind[e[3]] = self._by_kind.get(e[3], 0) - e[2]
                self._kind_detail_drop_locked(eid)
                self._tenant_charge_locked(e[5], -e[2])
                if eid in self._host or eid in self._disk:
                    self.demotions += 1

    def evict_all(self) -> int:
        """Drop EVERY tracked HBM cache entry (device-OOM recovery:
        the RESOURCE_EXHAUSTED retry path drains all cached device
        tensors before re-launching).  Host-tier twins survive — the
        retry repopulates from host bytes instead of fragment
        re-assembly.  Returns the number of entries evicted."""
        with self._lock:
            victims = list(self._entries.values())
            n_demoted = sum(
                1 for vcache, vkey, *_ in victims
                if (id(vcache), vkey) in self._host
                or (id(vcache), vkey) in self._disk)
            self._entries.clear()
            self.total = 0
            self._per_device = 0
            self._by_kind.clear()
            self._kind_detail.clear()
            self._tenant_bytes.clear()
            self._prefetched.clear()
            self.evictions += len(victims)
            self.demotions += n_demoted
            # owner-dict pops stay under the lock (the admit() victim
            # discipline): released, a concurrent admit could insert a
            # fresh entry for the same key between our snapshot and
            # pop — we would drop ITS tensor while _entries still
            # tracks it, permanently skewing the byte accounting
            for vcache, vkey, *_rest in victims:
                vcache.pop(vkey, None)
        return len(victims)

    def note_oom_feedback(self) -> None:
        """One recovered RESOURCE_EXHAUSTED: shrink the HBM budget 10%
        (floored at MIN_BUDGET_BYTES) so the tier demotes harder — the
        backend told us our idea of free HBM was wrong; only retrying
        would hit the same wall on the next admission wave."""
        with self._lock:
            new = max(MIN_BUDGET_BYTES, int(self.budget * 0.9))
            if new < self.budget:
                self.budget = new
                self.oom_budget_shrinks += 1

    # ----------------------------------------------------------- views

    def stats(self) -> dict:
        with self._lock:
            return {"budget": self.budget, "total": self.total,
                    "entries": len(self._entries),
                    "evictions": self.evictions,
                    "admits": self.admits,
                    "high_water": self.high_water,
                    # what one chip holds when stacks shard over the
                    # [mesh] plan: sum of ceil(bytes / devices) — equal
                    # to total with the mesh off, total/axis when every
                    # entry shards (the /debug/devices + /debug/mesh
                    # per-device residency line)
                    "per_device": self._per_device,
                    # compressed-vs-dense residency split (the
                    # roaring-on-TPU capacity story; /debug/devices)
                    "kinds": {k: v for k, v in self._by_kind.items()
                              if v},
                    "tenants": {t: v for t, v
                                in self._tenant_bytes.items() if v},
                    "tiers": self._tier_stats_locked()}

    def tenant_stats(self) -> dict[str, dict]:
        """Per-tenant residency accounting — the residency half of
        GET /debug/tenants: HBM bytes, host-tier bytes, the HBM quota
        in force, and the demotion pressure charged to each tenant.
        Empty until a tenant-attributed admission happens."""
        with self._lock:
            names = (set(self._tenant_bytes)
                     | set(self._tenant_host_bytes)
                     | set(self._tenant_pressure))
            out = {}
            for t in sorted(names):
                d = {
                    "hbmBytes": self._tenant_bytes.get(t, 0),
                    "hostBytes": self._tenant_host_bytes.get(t, 0),
                    "pressure": self._tenant_pressure.get(t, 0),
                }
                q = self._tenant_quota_bytes(t, self.budget)
                if q:
                    d["hbmQuota"] = q
                out[t] = d
            return out

    def _tier_stats_locked(self) -> dict:
        return {
            "host": {
                "budget": _cfg.host_budget_bytes,
                "bytes": self._host_bytes,
                "entries": len(self._host),
            },
            "disk": {
                "path": _cfg.disk_path,
                "bytes": self._disk_bytes,
                "entries": len(self._disk),
            },
            "demotions": self.demotions,
            "hits": self.tier_hits,
            "misses": self.tier_misses,
            "spills": self.tier_spills,
            "spillDrops": self.tier_spill_drops,
            "diskHits": self.disk_hits,
            "fallbacks": self.fallbacks,
            "oomBudgetShrinks": self.oom_budget_shrinks,
            "budgetInitial": self.budget_initial,
            "prefetchUseful": self.prefetch_useful,
        }

    def resident_eids(self) -> list[tuple]:
        """The eids currently HBM-resident (LRU order, coldest first)
        — the prefetcher's eviction-victim pool: a prefetch promotion
        that would displace a HOTTER resident is a net loss and is
        gated on these."""
        with self._lock:
            return list(self._entries)

    def demote_coldest(self, scores: dict) -> float | None:
        """Demote the lowest-scored resident entry (``scores`` maps
        eid -> access score; unlisted residents score 0) — the
        prefetcher's victim selection.  A prefetch promotion that let
        the ordinary LRU eviction pick its victim displaces whatever
        was least-recently TOUCHED, which under a skewed mix is often
        a hot-but-not-just-now row — measured on the zipfian bench as
        prefetching making stalls WORSE.  Choosing the victim by the
        same access-frequency signal that chose the candidate turns
        the pair into a strict improvement and converges (once
        residents are the top-scored set, every candidate fails the
        prefetcher's score guard and the churn stops).  Only entries
        with a host/disk twin are eligible (a demotion must never turn
        into a drop).  Returns the victim's score, or None when
        nothing was eligible."""
        with self._lock:
            best = None
            best_score = None
            for eid in self._entries:
                if eid not in self._host and eid not in self._disk:
                    continue
                s = scores.get(eid, 0.0)
                if best_score is None or s < best_score:
                    best, best_score = eid, s
            if best is None:
                return None
            self._evict_one_locked(best)
            # _evict_one_locked counts an eviction; re-classify: this
            # was an explicit demotion decision, not budget pressure
            self.evictions -= 1
        from pilosa_tpu import observe as _observe

        if _observe.journal_on:
            # after self._lock: the journal takes its own lock
            _observe.emit("residency.demote", score=best_score)
        return best_score

    def host_candidates(self, limit: int = 64) -> list[HostEntry]:
        """Host-tier entries whose owner cache currently lacks them —
        the prefetcher's promotion candidates, most-recently-used
        first (the ranking layer re-orders by access score)."""
        with self._lock:
            out = [e for e in reversed(list(self._host.values()))
                   if e.key not in e.cache]
            return out[:limit]

    def top_entries(self, n: int = 20) -> list[dict]:
        """Largest tracked device/host cache entries, for the heap
        profile endpoint — on a framework whose risk register is memory
        layout, 'which stacks hold the bytes' is the first question a
        10B-scale operator asks."""
        with self._lock:
            entries = sorted(self._entries.values(), key=lambda e: -e[2])[:n]
        return [{"key": repr(key)[:160], "bytes": nbytes,
                 "kind": kind, "devices": devices,
                 **({"tenant": tenant} if tenant is not None else {})}
                for _, key, nbytes, kind, devices, tenant in entries]

    def close(self) -> None:
        """Drop spill files (reset/test teardown)."""
        with self._lock:
            for eid in list(self._disk):
                self._drop_disk_locked(eid)
            self._host.clear()
            self._host_bytes = 0
            self._tenant_host_bytes.clear()


_global: ResidencyManager | None = None
_global_lock = threading.Lock()


def manager() -> ResidencyManager:
    """The process-wide manager (one budget per process, like the
    reference's global syswrap caps)."""
    global _global
    with _global_lock:
        if _global is None:
            _global = ResidencyManager()
        return _global


def reset(budget_bytes: int | None = None) -> ResidencyManager:
    """Replace the global manager (tests; budget reconfiguration).
    Stops promotion workers and clears the tier config baseline so no
    cross-test state survives."""
    global _global, _baseline, _refs
    promoter().stop()
    with _global_lock:
        if _global is not None:
            _global.close()
        _global = ResidencyManager(budget_bytes)
        mgr = _global
    with _cfg_lock:
        _cfg.__init__()
        _baseline = None
        _refs = 0
    return mgr


# --------------------------------------------------------------------
# async promotion
# --------------------------------------------------------------------


class PromotionFlight:
    """One in-flight promotion (single-flight per eid).  Demand
    waiters park on ``event`` for a bounded slice of their deadline;
    ``ok`` says whether the owner-cache entry was installed."""

    __slots__ = ("event", "ok", "error", "prefetch")

    def __init__(self, prefetch: bool):
        self.event = threading.Event()
        self.ok = False
        self.error: BaseException | None = None
        self.prefetch = prefetch


class Promoter:
    """Bounded background promotion pool: host-tier entries move back
    onto device OFF the query path.  Single-flight per key; each job
    runs under the admission controller's ``internal`` class when one
    is wired (query saturation sheds promotions — the query that
    wanted it falls back to host compute instead of queueing).  A full
    queue sheds queued PREFETCH jobs before refusing demand work."""

    def __init__(self):
        self._lock = _lockcheck.lock("residency.promoter")
        self._queue: deque = deque()  # (HostEntry, PromotionFlight)
        self._flights: dict[tuple, PromotionFlight] = {}
        self._wake = threading.Event()
        # stop() bumps the epoch; workers retire when theirs is stale.
        # An Event-flag design had a zombie hazard: a worker blocked
        # past the join timeout would miss a flag that stop() cleared
        # for the next generation and run forever untracked.
        self._epoch = 0
        self._workers: list[threading.Thread] = []
        self.admission = None  # server assembly wires the controller
        self.promotions = 0
        self.failures = 0
        self.sheds = 0          # demand jobs refused (queue/admission)
        self.prefetch_issued = 0
        self.prefetch_completed = 0
        self.prefetch_shed = 0

    # ------------------------------------------------------- lifecycle

    def _ensure_started_locked(self) -> None:
        self._workers = [w for w in self._workers if w.is_alive()]
        want = _cfg.promote_workers
        while len(self._workers) < want:
            t = threading.Thread(target=self._run, daemon=True,
                                 args=(self._epoch,),
                                 name=f"residency-promote-"
                                      f"{len(self._workers)}")
            self._workers.append(t)
            t.start()

    def stop(self) -> None:
        """Retire the current worker generation and fail every
        queued/in-flight job (server close / test reset).  Restartable:
        the next submit spawns workers under the new epoch.  A worker
        mid-promotion finishes its job (the installed entry is
        token-guarded, so at worst it is stale accounting noise) and
        retires on its next loop — even past the bounded join."""
        with self._lock:
            self._epoch += 1
            workers, self._workers = self._workers, []
            drained = list(self._queue)
            self._queue.clear()
            flights = dict(self._flights)
            self._flights.clear()
        self._wake.set()
        for _, fl in drained:
            fl.error = RuntimeError("promoter stopped")
            fl.event.set()
        for fl in flights.values():
            fl.event.set()
        for w in workers:
            w.join(timeout=2)
        self.admission = None

    # ---------------------------------------------------------- submit

    def submit(self, ent: HostEntry,
               prefetch: bool = False) -> PromotionFlight | None:
        """Enqueue one promotion (or join the in-flight one).  Returns
        the flight, or None when the job was refused: a prefetch over
        a full queue is silently shed; a DEMAND job first evicts a
        queued prefetch to make room and is only refused when the
        queue is all demand work (the caller falls back to host
        compute — bounded, never queued behind an unbounded line)."""
        eid = ent.eid
        with self._lock:
            fl = self._flights.get(eid)
            if fl is not None:
                if not prefetch and fl.prefetch:
                    fl.prefetch = False  # demand upgrades the flight
                return fl
            if len(self._queue) >= _cfg.promote_queue:
                if prefetch:
                    self.prefetch_shed += 1
                    return None
                # demand pressure sheds prefetch work first
                for i, (qe, qf) in enumerate(self._queue):
                    if qf.prefetch:
                        del self._queue[i]
                        self._flights.pop(qe.eid, None)
                        qf.error = RuntimeError("shed for demand work")
                        qf.event.set()
                        self.prefetch_shed += 1
                        break
                else:
                    self.sheds += 1
                    return None
            fl = PromotionFlight(prefetch)
            self._flights[eid] = fl
            if prefetch:
                self.prefetch_issued += 1
                self._queue.append((ent, fl))
            else:
                # demand jobs jump the prefetch line
                self._queue.appendleft((ent, fl))
            self._ensure_started_locked()
        self._wake.set()
        return fl

    def queue_full(self) -> bool:
        """True when the promotion queue is at capacity — the
        prefetcher's don't-even-try signal (a shed prefetch must not
        demote its victim first)."""
        with self._lock:
            return len(self._queue) >= _cfg.promote_queue

    # ---------------------------------------------------------- worker

    def _run(self, epoch: int) -> None:
        from pilosa_tpu import faultinject as _fi

        while True:
            with self._lock:
                if self._epoch != epoch:
                    return  # a stop() retired this generation
                job = self._queue.popleft() if self._queue else None
                if job is None:
                    self._wake.clear()
            if job is None:
                self._wake.wait(0.25)
                continue
            ent, fl = job
            ticket = None
            adm = self.admission
            if adm is not None:
                try:
                    ticket = adm.try_acquire("internal")
                except Exception:
                    # admission saturated: shed this promotion — the
                    # demand waiter falls back to host compute, a
                    # prefetch just doesn't happen
                    self._resolve(ent, fl,
                                  RuntimeError("promotion shed by "
                                               "admission"))
                    continue
            try:
                if _fi.armed:
                    _fi.hit("residency.promote")
                value = ent.promote(ent.payload)
                # install + re-admit: dict store is GIL-atomic and
                # readers validate tokens, so a racing owner rebuild
                # at worst overwrites with an equivalent entry
                ent.cache[ent.key] = value
                manager().admit(ent.cache, ent.key, ent.nbytes,
                                kind=ent.kind, devices=ent.devices,
                                token=ent.token, host=ent.payload,
                                promote=ent.promote,
                                fallback=ent.fallback,
                                prefetched=fl.prefetch,
                                kind_detail=ent.kind_detail)
                fl.ok = True
                with self._lock:
                    self.promotions += 1
                    if fl.prefetch:
                        self.prefetch_completed += 1
                from pilosa_tpu import observe as _observe

                if _observe.journal_on:
                    _observe.emit("residency.promote",
                                  bytes=int(ent.nbytes),
                                  prefetch=bool(fl.prefetch))
                self._resolve(ent, fl, None)
            except BaseException as e:  # noqa: BLE001 — injected
                # failures (residency.promote failpoint) and real
                # placement errors resolve the flight; waiters fall
                # back to host compute
                with self._lock:
                    self.failures += 1
                self._resolve(ent, fl, e)
            finally:
                if ticket is not None:
                    ticket.release()

    def _resolve(self, ent: HostEntry, fl: PromotionFlight,
                 err: BaseException | None) -> None:
        fl.error = err
        with self._lock:
            if self._flights.get(ent.eid) is fl:
                del self._flights[ent.eid]
        fl.event.set()

    # ----------------------------------------------------------- views

    def stats(self) -> dict:
        with self._lock:
            return {
                "workers": len([w for w in self._workers
                                if w.is_alive()]),
                "queue": len(self._queue),
                "inFlight": len(self._flights),
                "promotions": self.promotions,
                "failures": self.failures,
                "sheds": self.sheds,
                "prefetchIssued": self.prefetch_issued,
                "prefetchCompleted": self.prefetch_completed,
                "prefetchShed": self.prefetch_shed,
            }


_promoter = Promoter()


def promoter() -> Promoter:
    """The process-wide promotion pool (one per process, like the
    manager — HBM and the host tier are process-wide by nature)."""
    return _promoter


def promote_wait_s(deadline=None) -> float:
    """The bounded demand-promotion wait: [residency] promote-wait-ms
    further capped by the request's remaining deadline — a query never
    parks on a promotion past the point it could still answer from
    host bytes in time."""
    wait = max(0.0, _cfg.promote_wait_ms / 1e3)
    if deadline is not None:
        try:
            wait = min(wait, max(0.0, deadline.remaining()))
        except Exception:
            pass
    return wait


# --------------------------------------------------------------------
# RESOURCE_EXHAUSTED evict-and-retry (shared by every dispatch site)
# --------------------------------------------------------------------


def run_with_oom_retry(fn):
    """Run one device dispatch; on a backend RESOURCE_EXHAUSTED, evict
    every residency-tracked device entry (host twins survive —
    demotion, not loss), shrink the HBM budget (note_oom_feedback) so
    the tier demotes harder going forward, and retry ONCE.  The shared
    wrapper behind the fused Count/Row/TopN, ragged-tape,
    container-gather and mesh dispatch sites — all counted under
    device.oom_retries."""
    try:
        return fn()
    except Exception as e:  # noqa: BLE001 — classify below
        if "RESOURCE_EXHAUSTED" not in str(e):
            raise
        from pilosa_tpu import devobs as _devobs
        from pilosa_tpu import observe as _observe

        _devobs.observer().note_oom_retry()
        if _observe.journal_on:
            _observe.emit("oom.retry")
        mgr = manager()
        mgr.note_oom_feedback()
        mgr.evict_all()
        return fn()
