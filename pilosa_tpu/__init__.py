"""pilosa_tpu — a TPU-native distributed bitmap index.

A brand-new framework with the capabilities of Pilosa (reference:
github.com/pilosa/pilosa/v2): sharded, replicated boolean matrices queried
with PQL set algebra — redesigned TPU-first:

- Fragments are dense uint32-packed bitmap tensors resident in HBM; PQL set
  algebra (Union/Intersect/Difference/Xor/Not/Shift) lowers to XLA bitwise
  HLO + popcount fused by jit, instead of the reference's per-container
  roaring loops (roaring/roaring.go:595-1023).
- Shard fan-out runs as shard_map/pjit over a jax.sharding.Mesh with ICI
  collectives (psum / OR-reduce), replacing the reference's HTTP
  scatter-gather mapReduce (executor.go:2455).
- The host-side control plane (storage hierarchy, PQL parsing, cluster
  membership, REST API) mirrors the reference's layer map (SURVEY.md §1).
"""

from pilosa_tpu.shardwidth import SHARD_WIDTH, shard_width
from pilosa_tpu.version import VERSION as __version__

_LAZY = {
    # public embedding surface, loaded on first touch so `import
    # pilosa_tpu` stays light (no jax/server imports)
    "Server": ("pilosa_tpu.server.server", "Server"),
    "API": ("pilosa_tpu.api", "API"),
    "Holder": ("pilosa_tpu.models.holder", "Holder"),
    "Executor": ("pilosa_tpu.parallel.executor", "Executor"),
    "IndexOptions": ("pilosa_tpu.models.index", "IndexOptions"),
    "FieldOptions": ("pilosa_tpu.models.field", "FieldOptions"),
    "parse": ("pilosa_tpu.pql", "parse"),
    "Config": ("pilosa_tpu.config", "Config"),
}

__all__ = ["SHARD_WIDTH", "shard_width", "__version__", *sorted(_LAZY)]


def __getattr__(name: str):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(f"module 'pilosa_tpu' has no attribute {name!r}")
    import importlib

    value = getattr(importlib.import_module(target[0]), target[1])
    globals()[name] = value  # cache: later accesses skip this hook
    return value


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
