"""Shard width configuration.

The column space is split into fixed-width shards; `pos = row * SHARD_WIDTH +
col % SHARD_WIDTH` addresses a bit inside a fragment (reference:
fragment.go:50-53,3090 and shardwidth/*.go, where width is a build-tag in
2^16..2^32, default 2^20).

Here the width is a process-wide setting, configurable via the
PILOSA_TPU_SHARD_WIDTH_EXP environment variable (exponent, default 20) so the
test suite can exercise width independence the way the reference's
SHARD_WIDTH=22 CI matrix job does (.circleci/config.yml:52-56).
"""

import os

# Exponent of the shard width.  2^20 columns = 2^15 uint32 words per row: a
# [rows, 32768] uint32 tensor per fragment — sized so row-batched bitwise ops
# tile well onto TPU vector units.
SHARD_WIDTH_EXP = int(os.environ.get("PILOSA_TPU_SHARD_WIDTH_EXP", "20"))

if not (16 <= SHARD_WIDTH_EXP <= 32):
    raise ValueError(
        f"PILOSA_TPU_SHARD_WIDTH_EXP must be in [16, 32], got {SHARD_WIDTH_EXP}"
    )

SHARD_WIDTH = 1 << SHARD_WIDTH_EXP


def shard_width() -> int:
    """Number of columns per shard."""
    return SHARD_WIDTH
