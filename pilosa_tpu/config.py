"""Configuration: one Config struct fed from three merged sources —
defaults < TOML file < PILOSA_TPU_* environment < CLI flags.

Parity target: the reference's server/config.go:48-200 Config struct
(TOML tags) and cmd/root.go:94 viper merge order (flags ⊃ env ⊃ file).
Every option is also settable programmatically by constructing Config
directly — the analog of the reference's functional ServerOptions
(server.go:86-295) used by tests and embedders."""

from __future__ import annotations

import os

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: same API under the old name
    import tomli as tomllib
from dataclasses import dataclass, field, fields


ENV_PREFIX = "PILOSA_TPU_"


@dataclass
class ClusterConfig:
    """[cluster] section (server/config.go:100-117), plus the
    failure-handling knobs of the chaos round (parallel/cluster.py
    circuit breakers, parallel/executor.py hedged replica reads — no
    reference analog; Pilosa pays the full RPC timeout per query to a
    slow-but-alive peer).  ``breaker-threshold`` consecutive transport
    failures open a peer's breaker (queries fast-fail to the next
    replica instead of paying the timeout); after
    ``breaker-cooldown`` seconds the breaker half-opens and one trial
    request (or a successful membership heartbeat probe) closes it.
    Hedging: once ``hedge-min-samples`` latency samples exist for a
    peer, a remote shard map still in flight past ``EWMA +
    hedge-deviations x EWMA-deviation`` (floored at ``hedge-min-ms``)
    is re-issued to the next replica and the first full result wins;
    hedges are bounded to ``hedge-max-fraction`` of RPC volume (0
    disables hedging)."""

    replicas: int = 1
    partitions: int = 256
    seeds: list[str] = field(default_factory=list)
    coordinator: bool = False
    long_query_time: float = 0.0  # seconds; 0 disables slow-query log
    breaker_threshold: int = 5
    breaker_cooldown: float = 5.0  # seconds open before half-open
    hedge_min_samples: int = 8
    hedge_deviations: float = 4.0
    hedge_min_ms: float = 20.0
    hedge_max_fraction: float = 0.1  # of RPC volume; 0 disables


@dataclass
class FaultinjectConfig:
    """[faultinject] — the failpoint registry (pilosa_tpu.faultinject).
    ``armed`` is a failpoint spec (``name=action;...`` — see the
    module docstring for the grammar) applied at server open; empty
    (the default) arms nothing and every compiled-in site stays on its
    zero-cost disarmed path.  Also armable live via
    ``POST /debug/failpoints``."""

    armed: str = ""


@dataclass
class AntiEntropyConfig:
    """[anti-entropy] (server/config.go:118), grown into the
    self-healing round's knobs (parallel/syncer.py).  ``jitter`` is
    the fraction of ``interval`` each wait is randomized by (so a
    fleet restarted together does not run every AE sweep in lockstep);
    ``round-budget`` (seconds, 0 = unbounded) time-slices each sweep —
    a slice stops at the budget and the next one resumes from the
    persisted (index, field, view, shard) cursor, so a huge holder
    never monopolizes the internal admission class;
    ``peer-timeout`` bounds every peer exchange (block checksums,
    block data, diff pushes, attribute blocks) so one hung peer costs
    at most that, never a stalled round."""

    interval: float = 600.0  # seconds (reference default 10m)
    jitter: float = 0.1  # fraction of interval; 0 disables
    round_budget: float = 0.0  # seconds per slice; 0 = whole holder
    peer_timeout: float = 2.0  # seconds per peer exchange


@dataclass
class ReplicationConfig:
    """[replication] — degraded-write semantics + hinted handoff
    (parallel/hints.py; no reference analog — Pilosa fails the write
    when any owner replica is unreachable).  ``write-policy = "all"``
    (the default) keeps that all-owners guarantee byte-identical;
    ``"available"`` commits the write on the reachable owners and
    queues a HINT per missed delivery, replayed by a background worker
    once the peer's breaker closes or a heartbeat proves it alive —
    anti-entropy remains the backstop.  ``hint-max-bytes`` bounds the
    node's total queued hints (0 disables the queue);
    ``hint-max-age`` (seconds) drops hints too old to be the honest
    repair; ``replay-interval`` (seconds) is the drain scan period."""

    write_policy: str = "all"  # all | available
    hint_max_bytes: int = 16 << 20
    hint_max_age: float = 3600.0
    replay_interval: float = 0.5


@dataclass
class RebalanceConfig:
    """[rebalance] — online shard migration (parallel/rebalance.py; no
    reference analog — Pilosa gates the whole cluster RESIZING).
    ``transfer-budget`` caps concurrent shard backfills so migration
    traffic (admission class internal) never starves serving;
    ``dual-write-policy = "hint"`` (the default) commits writes on the
    serving owners and never fails a write over an unreachable pending
    owner (the miss is queued as a [replication] hint), ``"strict"``
    holds pending owners to the configured write-policy;
    ``cursor-path`` overrides where the coordinator persists its
    resumable plan cursor (default ``<data-dir>/.rebalance``);
    ``backoff-base``/``backoff-cap`` (seconds) shape the exponential
    pause when a transfer target's breaker opens mid-backfill;
    ``peer-timeout`` bounds each transfer exchange."""

    transfer_budget: int = 2
    dual_write_policy: str = "hint"  # hint | strict
    cursor_path: str = ""
    backoff_base: float = 0.2
    backoff_cap: float = 30.0
    peer_timeout: float = 2.0


@dataclass
class MetricConfig:
    """[metric] (server/config.go:125-133)."""

    service: str = "mem"  # mem | statsd | nop
    host: str = "127.0.0.1:8125"  # statsd agent address
    poll_interval: float = 0.0  # runtime gauge sweep seconds; 0 = off
    diagnostics: bool = False  # no phone-home by default


@dataclass
class TracingConfig:
    """[tracing] (server/config.go:141-149).  ``endpoint`` points at an
    OTLP/HTTP collector (…/v1/traces is appended); empty with
    enabled=true records in-memory only."""

    enabled: bool = False
    endpoint: str = ""


@dataclass
class ProfileConfig:
    """[profile] (server/config.go:151-156 — the reference's
    block/mutex profile rate knobs).  ``heap`` starts tracemalloc at
    server open, feeding ``GET /debug/pprof/heap``; ``heap_frames`` is
    the retained traceback depth per allocation — tracemalloc's cost
    knob (it has no sampling rate; depth is its dial, deeper = more
    useful stacks, more overhead).  Documented deviation: Python has no
    block/mutex profile; the wall-clock sampler at /debug/pprof/profile
    covers lock waits."""

    heap: bool = False
    heap_frames: int = 4


@dataclass
class CoalescerConfig:
    """[coalescer] — cross-query micro-batched dispatch (no reference
    analog; the serving-side batching lever for the TPU dispatch
    floor, parallel/coalescer.py).  ``enabled`` is tri-state:
    ``"auto"`` turns batching on only when an accelerator is attached
    (on a host-mode CPU dispatch is free and the window would only add
    latency); TOML booleans / "true"/"false" force it."""

    enabled: str = "auto"  # auto | true | false
    window_ms: float = 2.0
    max_batch: int = 32


@dataclass
class RaggedConfig:
    """[ragged] — heterogeneous-shape megabatch execution
    (ops/tape.py + parallel/coalescer.py; no reference analog — the
    Ragged-Paged-Attention-style batching lever for structurally
    diverse query traffic).  With ``enabled`` on, the coalescer keys
    its batching window on tape SIZE CLASS instead of exact expression
    shape, so distinct Count trees share one device launch through the
    op-tape interpreter.  ``max-tape``/``max-leaves`` cap the
    per-query tape; a query over either cap falls back to the
    per-shape fused path for that query alone (behavior unchanged).
    ``prewarm`` lowers the bucket interpreter programs on a background
    thread at server open so the first heterogeneous window pays a
    dispatch, not an XLA compile.  Only meaningful where the coalescer
    itself is on (accelerator attached, or [coalescer] forced true)."""

    enabled: bool = True
    max_tape: int = 32
    max_leaves: int = 16
    prewarm: bool = True


@dataclass
class VMConfig:
    """[vm] — the Pallas bitmap VM (ops/pallas_kernels.vm_counts +
    ops/tape.execute_vm; no reference analog — the one-kernel fusion
    of the ragged tape interpreter with the compressed container
    engine).  With ``enabled`` on, a coalesced sparse Count batch
    whose every leaf stages compressed executes as ONE scalar-prefetch
    kernel over the pooled containers, never materializing a dense
    register file.  ``min-domain`` is the floor a staged query's
    padded container-domain width rounds up to (keeps lowered-variant
    counts down and gives empty-domain queries a real batch slot);
    ``max-prefetch`` caps the per-launch scalar-prefetch directory in
    int32 entries (slots x batch x domain live in SMEM on chip —
    oversized batches split in two, oversized single queries route
    the dense engines).  Rides [ragged]: disabling the ragged engine
    disables the VM too, and ``?novm=1`` is the per-request escape."""

    enabled: bool = True
    min_domain: int = 8
    max_prefetch: int = 65536


@dataclass
class ObserveConfig:
    """[observe] — the query flight recorder (pilosa_tpu.observe; no
    reference analog beyond ``cluster.long-query-time``).  ``enabled``
    keeps the per-query record assembly on (sub-1% of the coalesced
    Count path, benchmarked in bench.py extras.observe); ``recent`` is
    the ring-buffer depth behind ``GET /debug/queries``;
    ``long_query_time`` (seconds, 0 = off) logs PQL + trace id + the
    stage breakdown for queries over the threshold — the reference's
    LongQueryTime with a profile attached.

    Device-runtime telemetry (pilosa_tpu.devobs):
    ``device_sample_interval`` (seconds, 0 = off) runs the background
    sampler that pushes ``device.*``/``compile.*``/``residency.*``
    gauges into the stats backends — pull scrapers get fresh gauges at
    /metrics anyway, so the loop only matters for push (statsd)
    deployments; ``fanin_timeout`` (seconds) bounds each peer fetch of
    the cluster-wide ``GET /debug/cluster/*`` merge.

    Engine observatory (pilosa_tpu.perfobs):
    ``device_peak_gbps`` is the memory-bandwidth roof the per-engine
    achieved GB/s is reported against (``bw_util`` on /debug/cost and
    in chip captures); 0 (the default) picks a datasheet ballpark per
    jax device kind — set it when the exact part's roof is known.
    ``profiler_max_seconds`` auto-stops an on-demand device profiler
    capture (``POST /debug/profiler/start``) that was never stopped
    (0 disables the deadline — captures then run until the explicit
    stop).

    Cluster event journal (pilosa_tpu.observe.EventJournal):
    ``journal`` keeps the structured state-transition ring behind
    ``GET /debug/events`` on (disarmed cost is one module-bool read,
    benchmarked in bench.py extras.traceasm); ``journal_size`` is the
    ring depth; ``journal_kinds`` is a comma-separated kind-prefix
    allowlist (empty = keep every kind) — filtered emissions tick the
    drop counter so a too-narrow filter is visible."""

    enabled: bool = True
    recent: int = 256
    long_query_time: float = 0.0  # seconds; 0 disables slow-query log
    device_sample_interval: float = 0.0  # seconds; 0 = scrape-time only
    fanin_timeout: float = 2.0  # seconds per peer in /debug/cluster/*
    device_peak_gbps: float = 0.0  # GB/s roof; 0 = per-device default
    profiler_max_seconds: float = 30.0  # capture auto-stop; 0 = never
    journal: bool = True  # the cluster event journal ring
    journal_size: int = 2048  # event ring depth
    journal_kinds: str = ""  # comma-separated kind prefixes; "" = all


@dataclass
class CostConfig:
    """[cost] — the shadow cost model (pilosa_tpu.perfobs; no
    reference analog — the stepping stone to a cost-based planner,
    ROADMAP item 4).  With ``shadow`` on (the default), the
    executor/coalescer consult the observed-cost table AFTER choosing
    an engine: the table's verdict is stamped onto the flight record
    (``wouldChoose``/``costDisagree``) and ``cost.disagreements``
    ticks, while routing itself stays byte-identical to a consult-free
    build — there is no active mode yet.  ``shadow = false`` turns the
    consult off entirely (per-launch samples still collect)."""

    shadow: bool = True


@dataclass
class CacheConfig:
    """[cache] — the generation-stamped query result cache
    (runtime/resultcache.py; the reference's per-fragment rank cache,
    cache.go:136, generalized to whole PQL subtrees).  ``budget-bytes``
    bounds total host memory held by cached results (strict — never
    exceeded, LRU evicts); ``max-entry-bytes`` refuses any single
    result larger than this (a giant Row result must not flush the
    warm working set); ``ttl`` (seconds, 0 = none) ages entries out on
    top of generation invalidation — generations already catch every
    local write, so a TTL only matters as a backstop against external
    clock-based staleness policies.  Per-request opt-out: ``?nocache=1``
    on the query route."""

    enabled: bool = True
    budget_bytes: int = 128 << 20
    max_entry_bytes: int = 8 << 20
    ttl: float = 0.0  # seconds; 0 disables age-based expiry


@dataclass
class IngestConfig:
    """[ingest] — the streaming write path (pilosa_tpu.ingest; the
    reference's roaring op-log appended ahead of snapshots,
    fragment.go import paths).  With ``delta-enabled`` on, batched
    imports and set/clear land in a bounded per-fragment DELTA PLANE
    without bumping the base generation — device residency and
    result-cache entries stay warm under sustained ingest — and the
    background compactor merges deltas into base roaring state under
    admission's ``internal`` class.  ``delta-budget-bytes`` bounds
    process-wide pending delta memory (past it the writer flushes its
    own fragment inline); ``compact-threshold-bits`` merges a fragment
    once its delta holds that many pending bit positions;
    ``compact-interval`` (seconds) is both the compactor scan period
    and the age bound (a delta older than one interval merges even
    when small).  Per-request escape: ``?nodelta=1`` on the query
    route compacts up front and reads pure base state."""

    delta_enabled: bool = True
    delta_budget_bytes: int = 64 << 20
    compact_threshold_bits: int = 1 << 17
    compact_interval: float = 2.0


@dataclass
class ContainersConfig:
    """[containers] — the compressed container-directory device layout
    (ops/containers.py; the reference's entire performance story:
    Chambi et al. / Lemire et al. roaring container specialization,
    ported to device).  With ``enabled`` on, fused Row/Count reads
    whose leaf rows are sparse execute over pooled non-empty 2^16-bit
    container blocks — resident device bytes track real data instead
    of shards x shard-width, and absent containers are skipped
    entirely.  ``threshold`` is the per-fragment fill-ratio ceiling
    (set bits / shard width) above which a row is considered hot and
    the query keeps the dense fused path (the dense layout is the
    right engine for hot rows).  Per-request escape:
    ``?nocontainers=1`` on the query route — results are bit-identical
    either way.

    ``kinds`` turns on per-container kind specialization (bitmap vs
    sorted-array vs run-interval pools — the full roaring triple on
    device); ``array-max`` is the cardinality ceiling for the array
    kind (canonical roaring uses 4096; lower values only NARROW the
    device pick — serialization always uses the canonical constant);
    ``run-cap`` caps how many intervals a run container may carry
    before it demotes to array/bitmap on device.  With ``kinds`` off
    every container stays a dense 2048-word block — results are
    bit-identical either way."""

    enabled: bool = True
    threshold: float = 0.25
    kinds: bool = True
    array_max: int = 4096
    run_cap: int = 256


@dataclass
class ResidencyConfig:
    """[residency] — tiered device-memory residency
    (runtime/residency.py; reference analog: the syswrap-capped mmap
    plus file-handle/map LRU that lets Pilosa serve fragments far
    beyond RAM).  ``host-budget-bytes`` caps the host-RAM tier behind
    HBM (0 disables tiering: misses rebuild inline, evictions drop —
    the pre-tier behavior); ``disk-path``/``disk-budget-bytes``
    optionally put a spill tier behind host RAM.
    ``promote-workers``/``promote-queue`` size the async promotion
    pool (each job runs under admission's ``internal`` class; a full
    queue sheds prefetch work first); ``promote-wait-ms`` bounds how
    long a demand miss parks on its promotion before taking the
    host-compute fallback (further capped by the request deadline).
    ``prefetch``/``prefetch-interval`` drive the predictive
    prefetcher (runtime/prefetch.py).  Per-request escape:
    ``?notiers=1`` on the query route — results are byte-identical
    either way."""

    host_budget_bytes: int = 1 << 30
    disk_path: str = ""
    disk_budget_bytes: int = 4 << 30
    promote_workers: int = 2
    promote_queue: int = 64
    promote_wait_ms: float = 50.0
    prefetch: bool = True
    prefetch_interval: float = 0.25


@dataclass
class MeshConfig:
    """[mesh] — mesh-native SPMD execution of the fused serving path
    (parallel/meshexec.py; no reference analog — Pilosa's only
    scale-out is host map-reduce over shards, executor.go:2455).
    With ``enabled`` on, fused-operand stacks lay out across a named
    device mesh via NamedSharding and the fused / ragged-tape /
    container-gather programs run under shard_map with collective
    reductions on the shard axis, so ONE launch evaluates a query (or
    a coalesced megabatch) across every local chip.  ``enabled`` is
    tri-state like the coalescer's: ``"auto"`` activates exactly when
    it can help (more than one local device, single process, not host
    mode).  ``axis-size`` bounds how many local devices join the
    shard axis (0 = all of them).  Per-request escape: ``?nomesh=1``
    on the query route — the pre-mesh single-device programs, results
    byte-identical."""

    enabled: str = "auto"  # auto | true | false
    axis_size: int = 0  # local devices on the shard axis; 0 = all


@dataclass
class AdmissionConfig:
    """[admission] — priority-classed admission control + load
    shedding on the serving path (serve/admission.py; no reference
    analog — the overload story Pilosa punts on).  Three classes, each
    with a concurrency cap and a bounded FIFO wait queue: ``query``
    (user PQL), ``ingest`` (imports), ``internal`` (anti-entropy,
    resize transfer, translate replication).  ``default_deadline``
    (seconds, 0 = none) applies to requests that carry no
    ``X-Pilosa-Deadline`` header.  Overflow sheds with 429/503 +
    Retry-After instead of queueing unboundedly."""

    enabled: bool = True
    query_cap: int = 32
    query_queue: int = 128
    ingest_cap: int = 16
    ingest_queue: int = 64
    internal_cap: int = 16
    internal_queue: int = 64
    default_deadline: float = 0.0  # seconds; 0 = no implied deadline


@dataclass
class TenantsConfig:
    """[tenants] — per-tenant isolation (serve/tenant.py; no reference
    analog — the reference's executor has no notion of who a query
    belongs to).  Disabled by default: a config with no [tenants]
    table is byte-identical to pre-tenant behavior.  With ``enabled``
    on, every request's tenant id (X-Pilosa-Tenant / ?tenant=; absent
    = the default tier) is scheduled fairly inside each admission
    class (``share`` concurrency slots + deficit-round-robin dequeue
    weight, ``queue`` bounded per-class wait depth), charged a soft
    ``cache-share`` fraction of the result-cache budget (eviction
    prefers an over-budget tenant's own entries), and held to a
    ``residency-share`` HBM/host-tier quota (an over-quota working
    set demotes its own stacks).  ``default-*`` are the quota of every
    tenant without its own ``[tenants.quotas.<name>]`` table entry;
    ``quotas`` maps tenant name -> {share, queue, cache-share,
    residency-share} (env form:
    ``name:share[:queue[:cache_share[:residency_share]]],...``)."""

    enabled: bool = False
    default_share: int = 4
    default_queue: int = 16
    default_cache_share: float = 0.25
    default_residency_share: float = 0.5
    quotas: dict = field(default_factory=dict)


@dataclass
class TLSConfig:
    """[tls] (server/tlsconfig.go; config server/config.go:58-66)."""

    certificate_path: str = ""
    key_path: str = ""
    skip_verify: bool = False


@dataclass
class Config:
    data_dir: str = "~/.pilosa_tpu"
    bind: str = "127.0.0.1:10101"
    name: str = ""
    verbose: bool = False
    log_path: str = ""
    max_writes_per_request: int = 5000
    # process-wide cap on long-lived WAL fds (reference syswrap
    # max-file-count, syswrap/os.go:41); runtime/filebudget.py LRU
    max_wal_files: int = 512
    heartbeat_interval: float = 0.0  # seconds; 0 disables the detector
    cluster: ClusterConfig = field(default_factory=ClusterConfig)
    anti_entropy: AntiEntropyConfig = field(default_factory=AntiEntropyConfig)
    replication: ReplicationConfig = field(
        default_factory=ReplicationConfig)
    rebalance: RebalanceConfig = field(default_factory=RebalanceConfig)
    metric: MetricConfig = field(default_factory=MetricConfig)
    tracing: TracingConfig = field(default_factory=TracingConfig)
    profile: ProfileConfig = field(default_factory=ProfileConfig)
    tls: TLSConfig = field(default_factory=TLSConfig)
    coalescer: CoalescerConfig = field(default_factory=CoalescerConfig)
    ragged: RaggedConfig = field(default_factory=RaggedConfig)
    vm: VMConfig = field(default_factory=VMConfig)
    observe: ObserveConfig = field(default_factory=ObserveConfig)
    cost: CostConfig = field(default_factory=CostConfig)
    admission: AdmissionConfig = field(default_factory=AdmissionConfig)
    cache: CacheConfig = field(default_factory=CacheConfig)
    ingest: IngestConfig = field(default_factory=IngestConfig)
    containers: ContainersConfig = field(
        default_factory=ContainersConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    residency: ResidencyConfig = field(default_factory=ResidencyConfig)
    faultinject: FaultinjectConfig = field(
        default_factory=FaultinjectConfig)
    tenants: TenantsConfig = field(default_factory=TenantsConfig)

    # ------------------------------------------------------------- access

    @property
    def host(self) -> str:
        return self.bind.rsplit(":", 1)[0] or "127.0.0.1"

    @property
    def port(self) -> int:
        parts = self.bind.rsplit(":", 1)
        return int(parts[1]) if len(parts) == 2 and parts[1] else 10101

    def expanded_data_dir(self) -> str:
        return os.path.expanduser(self.data_dir)

    # ------------------------------------------------------------ sources

    @classmethod
    def load(cls, toml_path: str | None = None,
             env: dict | None = None,
             overrides: dict | None = None) -> "Config":
        """defaults < TOML < env < overrides (cmd/root.go:94)."""
        cfg = cls()
        if toml_path:
            with open(toml_path, "rb") as f:
                cfg._apply_dict(tomllib.load(f))
        cfg._apply_env(env if env is not None else os.environ)
        if overrides:
            cfg._apply_dict(overrides)
        return cfg

    def _apply_dict(self, d: dict) -> None:
        for k, v in d.items():
            key = k.replace("-", "_")
            if key in ("cluster", "anti_entropy", "replication",
                       "rebalance", "metric", "tracing",
                       "profile", "tls", "coalescer", "ragged", "vm",
                       "observe", "cost", "admission", "cache",
                       "ingest", "containers", "mesh", "residency",
                       "faultinject", "tenants") and isinstance(v, dict):
                section = getattr(self, key)
                for sk, sv in v.items():
                    sname = sk.replace("-", "_")
                    if hasattr(section, sname):
                        setattr(section, sname, sv)
            elif hasattr(self, key) and not isinstance(getattr(self, key),
                                                       (ClusterConfig,
                                                        AntiEntropyConfig,
                                                        ReplicationConfig,
                                                        RebalanceConfig,
                                                        MetricConfig,
                                                        TracingConfig,
                                                        ProfileConfig,
                                                        TLSConfig,
                                                        CoalescerConfig,
                                                        RaggedConfig,
                                                        VMConfig,
                                                        ObserveConfig,
                                                        CostConfig,
                                                        AdmissionConfig,
                                                        CacheConfig,
                                                        IngestConfig,
                                                        ContainersConfig,
                                                        MeshConfig,
                                                        ResidencyConfig,
                                                        FaultinjectConfig,
                                                        TenantsConfig)):
                setattr(self, key, v)

    def _apply_env(self, env: dict) -> None:
        """PILOSA_TPU_BIND=..., PILOSA_TPU_CLUSTER_REPLICAS=2, etc.
        (the reference's PILOSA_* envs, cmd/root.go:94)."""
        for f in fields(self):
            if f.name in ("cluster", "anti_entropy", "replication",
                          "rebalance", "metric", "tracing",
                          "profile", "tls", "coalescer", "ragged",
                          "vm", "observe", "cost", "admission",
                          "cache", "ingest", "containers", "mesh",
                          "residency", "faultinject", "tenants"):
                section = getattr(self, f.name)
                for sf in fields(section):
                    key = f"{ENV_PREFIX}{f.name}_{sf.name}".upper()
                    if key in env:
                        setattr(section, sf.name,
                                _coerce(env[key], getattr(section, sf.name)))
            else:
                key = f"{ENV_PREFIX}{f.name}".upper()
                if key in env:
                    setattr(self, f.name,
                            _coerce(env[key], getattr(self, f.name)))

    # ------------------------------------------------------------- render

    def to_toml(self) -> str:
        """Effective config as TOML (reference `pilosa config` /
        generate-config, ctl/config.go)."""
        lines = [
            f'data-dir = "{self.data_dir}"',
            f'bind = "{self.bind}"',
            f'name = "{self.name}"',
            f"verbose = {str(self.verbose).lower()}",
            f'log-path = "{self.log_path}"',
            f"max-writes-per-request = {self.max_writes_per_request}",
            f"max-wal-files = {self.max_wal_files}",
            f"heartbeat-interval = {self.heartbeat_interval}",
            "",
            "[cluster]",
            f"replicas = {self.cluster.replicas}",
            f"partitions = {self.cluster.partitions}",
            f"seeds = [{', '.join(repr(s) for s in self.cluster.seeds)}]",
            f"coordinator = {str(self.cluster.coordinator).lower()}",
            f"long-query-time = {self.cluster.long_query_time}",
            f"breaker-threshold = {self.cluster.breaker_threshold}",
            f"breaker-cooldown = {self.cluster.breaker_cooldown}",
            f"hedge-min-samples = {self.cluster.hedge_min_samples}",
            f"hedge-deviations = {self.cluster.hedge_deviations}",
            f"hedge-min-ms = {self.cluster.hedge_min_ms}",
            f"hedge-max-fraction = {self.cluster.hedge_max_fraction}",
            "",
            "[anti-entropy]",
            f"interval = {self.anti_entropy.interval}",
            f"jitter = {self.anti_entropy.jitter}",
            f"round-budget = {self.anti_entropy.round_budget}",
            f"peer-timeout = {self.anti_entropy.peer_timeout}",
            "",
            "[replication]",
            f'write-policy = "{self.replication.write_policy}"',
            f"hint-max-bytes = {self.replication.hint_max_bytes}",
            f"hint-max-age = {self.replication.hint_max_age}",
            f"replay-interval = {self.replication.replay_interval}",
            "",
            "[rebalance]",
            f"transfer-budget = {self.rebalance.transfer_budget}",
            f'dual-write-policy = "{self.rebalance.dual_write_policy}"',
            f'cursor-path = "{self.rebalance.cursor_path}"',
            f"backoff-base = {self.rebalance.backoff_base}",
            f"backoff-cap = {self.rebalance.backoff_cap}",
            f"peer-timeout = {self.rebalance.peer_timeout}",
            "",
            "[metric]",
            f'service = "{self.metric.service}"',
            f'host = "{self.metric.host}"',
            f"poll-interval = {self.metric.poll_interval}",
            f"diagnostics = {str(self.metric.diagnostics).lower()}",
            "",
            "[tracing]",
            f"enabled = {str(self.tracing.enabled).lower()}",
            f'endpoint = "{self.tracing.endpoint}"',
            "",
            "[profile]",
            f"heap = {str(self.profile.heap).lower()}",
            f"heap-frames = {self.profile.heap_frames}",
            "",
            "[coalescer]",
            f'enabled = "{self.coalescer.enabled}"',
            f"window-ms = {self.coalescer.window_ms}",
            f"max-batch = {self.coalescer.max_batch}",
            "",
            "[ragged]",
            f"enabled = {str(self.ragged.enabled).lower()}",
            f"max-tape = {self.ragged.max_tape}",
            f"max-leaves = {self.ragged.max_leaves}",
            f"prewarm = {str(self.ragged.prewarm).lower()}",
            "",
            "[vm]",
            f"enabled = {str(self.vm.enabled).lower()}",
            f"min-domain = {self.vm.min_domain}",
            f"max-prefetch = {self.vm.max_prefetch}",
            "",
            "[observe]",
            f"enabled = {str(self.observe.enabled).lower()}",
            f"recent = {self.observe.recent}",
            f"long-query-time = {self.observe.long_query_time}",
            f"device-sample-interval = "
            f"{self.observe.device_sample_interval}",
            f"fanin-timeout = {self.observe.fanin_timeout}",
            f"device-peak-gbps = {self.observe.device_peak_gbps}",
            f"profiler-max-seconds = "
            f"{self.observe.profiler_max_seconds}",
            f"journal = {str(self.observe.journal).lower()}",
            f"journal-size = {self.observe.journal_size}",
            f'journal-kinds = "{self.observe.journal_kinds}"',
            "",
            "[cost]",
            f"shadow = {str(self.cost.shadow).lower()}",
            "",
            "[admission]",
            f"enabled = {str(self.admission.enabled).lower()}",
            f"query-cap = {self.admission.query_cap}",
            f"query-queue = {self.admission.query_queue}",
            f"ingest-cap = {self.admission.ingest_cap}",
            f"ingest-queue = {self.admission.ingest_queue}",
            f"internal-cap = {self.admission.internal_cap}",
            f"internal-queue = {self.admission.internal_queue}",
            f"default-deadline = {self.admission.default_deadline}",
            "",
            "[cache]",
            f"enabled = {str(self.cache.enabled).lower()}",
            f"budget-bytes = {self.cache.budget_bytes}",
            f"max-entry-bytes = {self.cache.max_entry_bytes}",
            f"ttl = {self.cache.ttl}",
            "",
            "[ingest]",
            f"delta-enabled = {str(self.ingest.delta_enabled).lower()}",
            f"delta-budget-bytes = {self.ingest.delta_budget_bytes}",
            f"compact-threshold-bits = "
            f"{self.ingest.compact_threshold_bits}",
            f"compact-interval = {self.ingest.compact_interval}",
            "",
            "[containers]",
            f"enabled = {str(self.containers.enabled).lower()}",
            f"threshold = {self.containers.threshold}",
            f"kinds = {str(self.containers.kinds).lower()}",
            f"array-max = {self.containers.array_max}",
            f"run-cap = {self.containers.run_cap}",
            "",
            "[mesh]",
            f'enabled = "{self.mesh.enabled}"',
            f"axis-size = {self.mesh.axis_size}",
            "",
            "[residency]",
            f"host-budget-bytes = {self.residency.host_budget_bytes}",
            f'disk-path = "{self.residency.disk_path}"',
            f"disk-budget-bytes = {self.residency.disk_budget_bytes}",
            f"promote-workers = {self.residency.promote_workers}",
            f"promote-queue = {self.residency.promote_queue}",
            f"promote-wait-ms = {self.residency.promote_wait_ms}",
            f"prefetch = {str(self.residency.prefetch).lower()}",
            f"prefetch-interval = {self.residency.prefetch_interval}",
            "",
            "[faultinject]",
            f'armed = "{self.faultinject.armed}"',
            "",
            "[tenants]",
            f"enabled = {str(self.tenants.enabled).lower()}",
            f"default-share = {self.tenants.default_share}",
            f"default-queue = {self.tenants.default_queue}",
            f"default-cache-share = {self.tenants.default_cache_share}",
            f"default-residency-share = "
            f"{self.tenants.default_residency_share}",
            *[line
              for name, q in sorted(self.tenants.quotas.items())
              for line in _tenant_quota_toml(name, q)],
            "",
            "[tls]",
            f'certificate-path = "{self.tls.certificate_path}"',
            f'key-path = "{self.tls.key_path}"',
            f"skip-verify = {str(self.tls.skip_verify).lower()}",
        ]
        return "\n".join(lines) + "\n"


def _tenant_quota_toml(name: str, q) -> list[str]:
    """Render one [tenants.quotas.<name>] table (dict or TenantQuota)."""
    get = (q.get if isinstance(q, dict)
           else lambda k, d=None: getattr(q, k.replace("-", "_"), d))
    out = [f'[tenants.quotas."{name}"]']
    for key, default in (("share", 4), ("queue", 16),
                         ("cache-share", 0.25),
                         ("residency-share", 0.5)):
        v = get(key, None)
        if v is None and isinstance(q, dict):
            v = q.get(key.replace("-", "_"))
        out.append(f"{key} = {default if v is None else v}")
    return out


def _coerce(raw: str, current):
    if isinstance(current, dict):
        # tenant-quota spec: name:share[:queue[:cache:res]],...
        from pilosa_tpu.serve.tenant import parse_quota_spec

        return {n: {"share": q.share, "queue": q.queue,
                    "cache_share": q.cache_share,
                    "residency_share": q.residency_share}
                for n, q in parse_quota_spec(raw).items()}
    if isinstance(current, bool):
        return raw.lower() in ("1", "true", "yes", "on")
    if isinstance(current, int):
        return int(raw)
    if isinstance(current, float):
        return float(raw)
    if isinstance(current, list):
        return [s for s in raw.split(",") if s]
    return raw
