"""Serving-path protection: admission control + deadline propagation.

The accept-side gate between the HTTP surface (server/handler.py) and
the device dispatch path (parallel/executor.py, parallel/coalescer.py):
per-class concurrency caps with bounded wait queues (admission.py) and
end-to-end request deadlines (deadline.py) so overload degrades to
honest 429/503 + Retry-After instead of unbounded queueing, and
expired work is dropped before it ever reaches a device launch.
"""

from pilosa_tpu.serve.admission import (  # noqa: F401
    AdmissionController,
    CLASSES,
    ShedError,
    rpc_class,
)
from pilosa_tpu.serve.deadline import (  # noqa: F401
    Deadline,
    DeadlineExceededError,
)
