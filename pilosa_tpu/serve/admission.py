"""Admission control: priority-classed gating and load shedding for
the serving path.

The ROADMAP north star is heavy traffic from millions of users, yet a
stdlib ThreadingHTTPServer admits one unbounded thread per connection:
overload means unbounded queueing and latency collapse, with
anti-entropy and resize traffic competing head-to-head with user
queries.  This module is the process-wide gate between accept and
dispatch — the admission/batching discipline TPU serving stacks are
built around (Ragged Paged Attention, arxiv 2604.15464, exists because
TPU serving is admission-bound; DrJAX, arxiv 2403.07128, is the
map-reduce fan-out the deadline checks protect from expired
stragglers).

Three priority classes, strictly ordered:

- ``query``    — user PQL (highest; must never starve)
- ``ingest``   — import / import-value / import-roaring
- ``internal`` — syncer anti-entropy, resize fragment transfer,
  translate replication, cluster control messages (lowest)

Each class owns its own concurrency cap and bounded FIFO wait queue,
so classes are *isolated*: saturating ``internal`` cannot consume a
single ``query`` slot.  Load shedding is honest and lowest-class/
newest-first:

- a request arriving to a full class queue is refused (429 — the
  NEWEST request sheds; queued older requests keep their place);
- a request whose predicted queue wait exceeds its remaining deadline
  is refused up front (503) instead of timing out after burning a
  slot;
- ``internal`` arrivals yield (503) while the ``query`` queue is under
  pressure — the lowest class sheds first under saturation;
- a queued request whose deadline expires sheds with an ``expired``
  outcome (503) and never reaches dispatch.

Every refusal carries ``Retry-After`` derived from the class's EWMA
service time, so clients back off proportionally to actual load.

Per-tenant scheduling (the [tenants] table, serve/tenant.py): with
isolation enabled, every class additionally runs WEIGHTED FAIRNESS
across tenants *inside* its cap — each tenant holds at most its
``share`` of concurrent slots, queues in its own bounded FIFO
(arrivals past ``queue`` shed 429 ``tenant-queue-full`` — the "I am
over quota" signal, distinct from the class-wide ``queue-full``
"server is drowning" one), and freed slots dequeue by deficit round
robin weighted by ``share``, so a tenant flooding its queue drains at
exactly its configured proportion of class capacity while everyone
else's queue wait stays flat.  A per-tenant queue-wait EWMA feeds the
same deadline-unmeetable 503 machinery.  With [tenants] disabled
(the default) the tenant structures are never touched and behavior is
byte-identical to the class-only gate.

The ``admission.acquire`` failpoint (pilosa_tpu.faultinject) sits at
the top of :meth:`AdmissionController.acquire` — ``error(shed)``
injects a deterministic refusal, ``delay(ms)`` a queue-delay stall —
zero-cost disarmed like every other site.

Stats surface (per class, tag ``class:<name>``):
``admission.admitted``, ``admission.shed`` (tag ``reason:<why>``),
``admission.expired`` counters and the ``admission.queue_wait``
histogram (nanoseconds).  Per-tenant totals publish as the
``tenant.*`` gauge family at scrape time (serve/tenant.py).
"""

from __future__ import annotations

import functools
import math
import threading
import time
from collections import deque

from pilosa_tpu import faultinject as _fi
from pilosa_tpu import stats as _stats
from pilosa_tpu.serve import tenant as _tenant
from pilosa_tpu.serve.deadline import Deadline, tls_scope

#: Priority order: lower number = higher priority = sheds last.
PRIORITY = {"query": 0, "ingest": 1, "internal": 2}
CLASSES = tuple(sorted(PRIORITY, key=PRIORITY.get))

#: Hard ceiling on time spent queued without a deadline — a wedged
#: slot holder must not strand waiters forever.
MAX_QUEUE_WAIT_S = 60.0

#: Retry-After bounds (seconds).  The floor keeps the integer header
#: non-zero; the ceiling stops a long EWMA from telling clients to
#: disappear for minutes.
RETRY_AFTER_MIN_S = 1
RETRY_AFTER_MAX_S = 30


class ShedError(Exception):
    """A request refused (or expired) at the admission gate.  Carries
    the HTTP status the handler should answer with and the suggested
    Retry-After (seconds)."""

    def __init__(self, klass: str, reason: str, status: int,
                 retry_after: int, wait_ns: int = 0,
                 tenant: str | None = None):
        detail = f" (tenant {tenant})" if tenant else ""
        super().__init__(
            f"{klass} request {reason}{detail} "
            f"(admission control; retry after {retry_after}s)")
        self.klass = klass
        self.reason = reason  # queue-full | tenant-queue-full |
        #                       deadline-unmeetable | yield-to-query |
        #                       queue-timeout | expired
        self.status = status  # 429 (back off) or 503 (overloaded)
        self.retry_after = retry_after
        # time spent queued before the refusal (expired-in-queue) —
        # the shed flight record's queue-wait evidence
        self.wait_ns = wait_ns
        # the shedding tenant (isolation enabled): rides the
        # structured 429/503 body so a client can tell "I am over
        # quota" (tenant-queue-full) from "the server is drowning"
        self.tenant = tenant

    @property
    def outcome(self) -> str:
        """Flight-record outcome: ``expired`` for a spent deadline,
        ``shed`` for every capacity refusal."""
        return "expired" if self.reason == "expired" else "shed"


# --------------------------------------------------------------------
# outbound RPC class tagging
# --------------------------------------------------------------------

_tls_rpc = threading.local()  # .klass: class stamped on outbound RPC


class rpc_class(tls_scope):
    """Tag every outbound RPC issued inside the with-block with an
    admission class (the ``X-Pilosa-Class`` header, read by
    server/client.py).  Internal callers — syncer, resize, translate
    replication, broadcasts — wrap their send loops with
    ``rpc_class("internal")`` so their traffic lands in the receiving
    node's lowest class and can never starve user queries; the import
    fan-out tags its replica deliveries ``ingest``.  Re-entrant."""

    __slots__ = ()

    def __init__(self, klass: str):
        if klass not in PRIORITY:
            raise ValueError(f"unknown admission class: {klass!r}")
        super().__init__(_tls_rpc, "klass", klass)


def current_rpc_class() -> str | None:
    return getattr(_tls_rpc, "klass", None)


def tagged(klass: str):
    """Decorator form of :class:`rpc_class`: every RPC the function
    issues carries ``klass``.  The one-line spelling for internal call
    sites (syncer sweeps, resize jobs, translate tailing)."""
    if klass not in PRIORITY:
        raise ValueError(f"unknown admission class: {klass!r}")

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with rpc_class(klass):
                return fn(*args, **kwargs)

        return wrapper

    return deco


# --------------------------------------------------------------------
# controller
# --------------------------------------------------------------------

class _Waiter:
    __slots__ = ("event", "dl", "state", "tenant")

    def __init__(self, dl: Deadline | None, tenant: str | None = None):
        self.event = threading.Event()
        self.dl = dl
        self.state = "waiting"  # -> admitted | expired | abandoned
        self.tenant = tenant


class _TenantState:
    """One tenant's slot + queue accounting inside ONE class (guarded
    by the controller's lock).  ``deficit`` is the deficit-round-robin
    credit: each ring visit adds the tenant's share, each dequeued
    waiter spends 1 — a flooding tenant drains at its weight's
    proportion of freed slots, never faster."""

    __slots__ = ("in_flight", "waiters", "deficit", "admitted",
                 "shed", "expired", "wait_ewma_s")

    def __init__(self):
        self.in_flight = 0
        self.waiters: deque[_Waiter] = deque()
        self.deficit = 0.0
        self.admitted = 0
        self.shed = 0
        self.expired = 0
        self.wait_ewma_s = 0.0  # EWMA of observed queue waits


class _Gate:
    """One class's slot + queue accounting (guarded by the
    controller's lock).  ``tenants``/``rr``/``waiting_total`` are the
    per-tenant layer — untouched (and empty) while [tenants] is off."""

    __slots__ = ("cap", "depth", "in_flight", "waiters",
                 "ewma_service_s", "admitted", "shed", "expired",
                 "tenants", "rr", "waiting_total")

    def __init__(self, cap: int, depth: int):
        self.cap = max(1, int(cap))
        self.depth = max(0, int(depth))
        self.in_flight = 0
        self.waiters: deque[_Waiter] = deque()
        self.ewma_service_s = 0.0
        # local mirrors of the stats counters so /debug/admission works
        # even on a NOP stats backend
        self.admitted = 0
        self.shed = 0
        self.expired = 0
        # tenant name -> _TenantState; rr is the DRR ring of tenants
        # with queued waiters; waiting_total sums their queue lengths
        self.tenants: dict[str, _TenantState] = {}
        self.rr: deque[str] = deque()
        self.waiting_total = 0


class Ticket:
    """One admitted request's slot.  ``release()`` is idempotent and
    MUST run (the handler's finally) or the slot leaks."""

    __slots__ = ("_ctrl", "klass", "queue_wait_ns", "_t_admit",
                 "_released", "tenant")

    def __init__(self, ctrl: "AdmissionController | None", klass: str,
                 queue_wait_ns: int, tenant: str | None = None):
        self._ctrl = ctrl
        self.klass = klass
        self.queue_wait_ns = queue_wait_ns
        self._t_admit = time.monotonic()
        self._released = False
        self.tenant = tenant

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        if self._ctrl is not None:
            self._ctrl._release(self.klass, self._t_admit,
                                tenant=self.tenant)

    def info(self) -> dict:
        """The flight-record stamp (observe.admission_scope)."""
        d = {"class": self.klass, "queue_wait_ns": self.queue_wait_ns}
        if self.tenant is not None:
            d["tenant"] = self.tenant
        return d


class AdmissionController:
    """Process-wide admission gate: per-class token/slot accounting
    over bounded FIFO wait queues.  One per server; thread-safe."""

    def __init__(self, query_cap: int = 32, query_queue: int = 128,
                 ingest_cap: int = 16, ingest_queue: int = 64,
                 internal_cap: int = 16, internal_queue: int = 64,
                 default_deadline: float = 0.0, enabled: bool = True,
                 stats=None):
        self.enabled = enabled
        self.default_deadline = default_deadline  # s; 0 = none implied
        self.stats = stats if stats is not None else _stats.NOP
        self._lock = threading.Lock()
        self._gates = {
            "query": _Gate(query_cap, query_queue),
            "ingest": _Gate(ingest_cap, ingest_queue),
            "internal": _Gate(internal_cap, internal_queue),
        }

    # ------------------------------------------------------------ sizing

    def total_capacity(self) -> int:
        """Sum of class caps + queue depths — the bound on requests
        the gate will ever hold concurrently, and the basis for the
        accept-side handler-thread cap (server/handler.py)."""
        return sum(g.cap + g.depth for g in self._gates.values())

    # ----------------------------------------------------------- acquire

    def acquire(self, klass: str, dl: Deadline | None = None,
                tenant: str | None = None) -> Ticket:
        """Admit (possibly after a bounded FIFO wait) or raise
        ShedError.  Runs on the request's handler thread; the wait is
        event-based, never a spin.  ``tenant`` is the request's tenant
        id — consulted only while [tenants] isolation is enabled, in
        which case the request also clears its tenant's per-class
        quota (anonymous requests ride the default tier)."""
        g = self._gates.get(klass)
        if g is None:
            raise ValueError(f"unknown admission class: {klass!r}")
        if _fi.armed:
            # failpoint: deterministic overload/queue-delay chaos at
            # the gate itself — error(shed) refuses, delay(ms) stalls
            _fi.hit("admission.acquire")
        if not self.enabled:
            return Ticket(None, klass, 0)
        pol = _tenant.policy()
        tname = _tenant.resolve(tenant) if pol is not None else None
        t0 = time.perf_counter_ns()
        with self._lock:
            ts = None
            if pol is not None:
                ts = g.tenants.get(tname)
                if ts is None:
                    ts = g.tenants[tname] = _TenantState()
                quota = pol.quota_for(tname)
                share = max(1, quota.share)
            if dl is not None and dl.expired():
                g.expired += 1
                if ts is not None:
                    ts.expired += 1
                err = ShedError(klass, "expired", 503,
                                self._retry_after(g), tenant=tname)
            elif klass == "internal" and self._query_pressure_locked():
                # lowest class sheds first: anti-entropy/resize yield
                # while user queries are stacking up
                g.shed += 1
                if ts is not None:
                    ts.shed += 1
                err = ShedError(klass, "yield-to-query", 503,
                                self._retry_after(self._gates["query"]),
                                tenant=tname)
            elif ts is None and g.in_flight < g.cap and not g.waiters:
                g.in_flight += 1
                g.admitted += 1
                err = None
                w = None
            elif (ts is not None and g.in_flight < g.cap
                  and ts.in_flight < share and not ts.waiters):
                # a tenant under BOTH caps with no queued peers admits
                # straight through; other tenants' waiters are waiting
                # on their own quota or on slots the wake loop already
                # found occupied
                g.in_flight += 1
                g.admitted += 1
                ts.in_flight += 1
                ts.admitted += 1
                # a zero-wait admit decays the queue-wait EWMA (sample
                # 0) — without it a past congestion episode pins the
                # deadline-unmeetable floor high forever, since sheds
                # never sample and queued admits only happen when the
                # floor already let the request queue
                ts.wait_ewma_s *= 0.8
                err = None
                w = None
            elif ts is not None and len(ts.waiters) >= max(0, quota.queue):
                # the TENANT's queue is full: this client is over its
                # own quota — distinct reason (and tenant on the body)
                # so it can tell quota pressure from server overload
                g.shed += 1
                ts.shed += 1
                err = ShedError(klass, "tenant-queue-full", 429,
                                self._retry_after(g), tenant=tname)
            elif (len(g.waiters) if ts is None
                  else g.waiting_total) >= g.depth:
                # newest-first shedding: the ARRIVING request refuses;
                # queued older requests keep their place
                g.shed += 1
                if ts is not None:
                    ts.shed += 1
                err = ShedError(klass, "queue-full", 429,
                                self._retry_after(g), tenant=tname)
            elif (dl is not None
                  and (self._predicted_wait_s(g) if ts is None
                       else self._predicted_tenant_wait_s(g, ts, share))
                  > dl.remaining()):
                g.shed += 1
                if ts is not None:
                    ts.shed += 1
                err = ShedError(klass, "deadline-unmeetable", 503,
                                self._retry_after(g), tenant=tname)
            elif ts is None:
                err = None
                w = _Waiter(dl)
                g.waiters.append(w)
            else:
                err = None
                w = _Waiter(dl, tenant=tname)
                ts.waiters.append(w)
                g.waiting_total += 1
                if tname not in g.rr:
                    g.rr.append(tname)
        # stats emit OUTSIDE the lock (a slow/raising backend must not
        # serialize admission) and exception-proof (a raising backend
        # must never leak a slot or mask the shed signal)
        if err is not None:
            self._emit_shed(klass, err.reason)
            raise err
        if w is None:
            self._emit_admitted(klass, 0)
            return Ticket(self, klass, 0, tenant=tname)
        timeout = MAX_QUEUE_WAIT_S
        if dl is not None:
            timeout = min(timeout, max(0.0, dl.remaining()))
        w.event.wait(timeout)
        # classify at WAKE time: only a deadline that actually passed
        # is an expiry; timing out on the MAX_QUEUE_WAIT_S backstop
        # (no deadline, or a budget longer than the backstop) is a
        # capacity incident (wedged slot holder) and reports as a
        # shed — or operators chase client deadlines instead of the
        # stuck slot
        reason = ("expired" if dl is not None and dl.expired()
                  else "queue-timeout")
        wait_ns = time.perf_counter_ns() - t0
        with self._lock:
            admitted = w.state == "admitted"
            if admitted:
                g.admitted += 1
                if ts is not None:
                    ts.admitted += 1
                    wait_s = wait_ns / 1e9
                    ts.wait_ewma_s = (wait_s if ts.wait_ewma_s == 0.0
                                      else 0.8 * ts.wait_ewma_s
                                      + 0.2 * wait_s)
            else:
                # deadline (or the safety cap) expired while queued —
                # either noticed here or marked by a promoter
                if w.state == "waiting":
                    w.state = "abandoned"
                    try:
                        if ts is None:
                            g.waiters.remove(w)
                        else:
                            ts.waiters.remove(w)
                            g.waiting_total -= 1
                    except ValueError:
                        pass
                if reason == "expired":
                    g.expired += 1
                    if ts is not None:
                        ts.expired += 1
                else:
                    g.shed += 1
                    if ts is not None:
                        ts.shed += 1
        if admitted:
            self._emit_admitted(klass, wait_ns)
            return Ticket(self, klass, wait_ns, tenant=tname)
        self._emit_shed(klass, reason)
        raise ShedError(klass, reason, 503, self._retry_after(g),
                        wait_ns=wait_ns, tenant=tname)

    def try_acquire(self, klass: str) -> Ticket:
        """Non-blocking admit: a free slot (with no queued waiters
        ahead) or an immediate ShedError — never a queue wait.  The
        gate for opportunistic background work (tiered-residency
        promotions, prefetch): under saturation such work must SHED,
        not line up behind user traffic it exists to serve."""
        g = self._gates.get(klass)
        if g is None:
            raise ValueError(f"unknown admission class: {klass!r}")
        if not self.enabled:
            return Ticket(None, klass, 0)
        with self._lock:
            if (klass == "internal" and self._query_pressure_locked()) \
                    or g.in_flight >= g.cap or g.waiters \
                    or g.waiting_total:
                g.shed += 1
                err = ShedError(klass, "yield-to-query", 503,
                                self._retry_after(g))
            else:
                g.in_flight += 1
                g.admitted += 1
                err = None
        if err is not None:
            self._emit_shed(klass, err.reason)
            raise err
        self._emit_admitted(klass, 0)
        return Ticket(self, klass, 0)

    def _release(self, klass: str, t_admit: float,
                 tenant: str | None = None) -> None:
        with self._lock:
            g = self._gates[klass]
            g.in_flight -= 1
            if tenant is not None:
                ts = g.tenants.get(tenant)
                if ts is not None and ts.in_flight > 0:
                    ts.in_flight -= 1
            held = time.monotonic() - t_admit
            g.ewma_service_s = (held if g.ewma_service_s == 0.0
                                else 0.8 * g.ewma_service_s + 0.2 * held)
            while g.in_flight < g.cap and g.waiters:
                w = g.waiters.popleft()
                if w.state != "waiting":  # abandoned by its own thread
                    continue
                if w.dl is not None and w.dl.expired():
                    # expired in queue: wake it to shed; its own thread
                    # counts the expiry (exactly once, in acquire)
                    w.state = "expired"
                    w.event.set()
                    continue
                w.state = "admitted"
                g.in_flight += 1
                w.event.set()
                break
            if g.rr:
                self._wake_tenants_locked(g)

    def _wake_tenants_locked(self, g: _Gate) -> None:
        """Deficit-round-robin dequeue across the tenants with queued
        waiters: each ring visit credits a tenant its ``share``, each
        admitted waiter spends one credit, and a tenant never exceeds
        its per-class concurrency share — so freed capacity divides in
        weight proportion no matter how deep any one queue is.  Caller
        holds the controller lock."""
        pol = _tenant.policy()
        while g.in_flight < g.cap and g.rr:
            advanced = False
            for _ in range(len(g.rr)):
                if g.in_flight >= g.cap:
                    break
                tname = g.rr[0]
                ts = g.tenants.get(tname)
                if ts is None or not ts.waiters:
                    g.rr.popleft()
                    if ts is not None:
                        ts.deficit = 0.0
                    advanced = True
                    continue
                # [tenants] turned off with waiters still queued: fall
                # back to unweighted drain so nobody strands
                quota = pol.quota_for(tname) if pol is not None else None
                share = max(1, quota.share) if quota is not None else g.cap
                if ts.deficit < 1.0:
                    ts.deficit += share
                while (ts.deficit >= 1.0 and ts.waiters
                       and g.in_flight < g.cap
                       and ts.in_flight < share):
                    w = ts.waiters.popleft()
                    g.waiting_total -= 1
                    if w.state != "waiting":
                        # abandoned by its own thread: costs no credit
                        advanced = True
                        continue
                    if w.dl is not None and w.dl.expired():
                        w.state = "expired"
                        w.event.set()
                        advanced = True
                        continue
                    w.state = "admitted"
                    g.in_flight += 1
                    ts.in_flight += 1
                    ts.deficit -= 1.0
                    w.event.set()
                    advanced = True
                if (ts.waiters and ts.deficit >= 1.0
                        and ts.in_flight < share):
                    # unspent credit with queued waiters and tenant
                    # capacity: the class is full — stay at the ring
                    # front so the NEXT freed slot continues this
                    # tenant's turn (rotating here would flatten the
                    # weights to plain round robin whenever slots free
                    # one at a time, i.e. always)
                    break
                g.rr.rotate(-1)
            if not advanced:
                # every queued tenant is at its concurrency share (or
                # the class is full): nothing more can wake now
                break

    # ---------------------------------------------------------- policies

    def _query_pressure_locked(self) -> bool:
        """True while the query class is saturated AND its queue is at
        least half full — the signal for lower classes to yield.
        Tenant-queued waiters (waiting_total) count: with isolation on
        the class queue lives in the per-tenant deques."""
        q = self._gates["query"]
        return (q.depth > 0 and q.in_flight >= q.cap
                and 2 * (len(q.waiters) + q.waiting_total) >= q.depth)

    def _predicted_wait_s(self, g: _Gate) -> float:
        """Queue-position estimate: (waiters ahead + 1) drain at
        cap-parallel EWMA service time.  Zero until the first release
        seeds the EWMA — never shed on a guess with no evidence."""
        return (len(g.waiters) + 1) * g.ewma_service_s / g.cap

    def _predicted_tenant_wait_s(self, g: _Gate, ts: _TenantState,
                                 share: int) -> float:
        """Per-tenant queue-position estimate: the tenant's waiters
        drain at ITS share of class parallelism (never the full cap —
        an over-quota tenant's queue moves at its weight), floored by
        the tenant's observed queue-wait EWMA so a tenant whose waits
        have been long sheds honestly even while its queue is short."""
        eff = max(1, min(share, g.cap))
        return max((len(ts.waiters) + 1) * g.ewma_service_s / eff,
                   ts.wait_ewma_s)

    def _retry_after(self, g: _Gate) -> int:
        return int(min(RETRY_AFTER_MAX_S,
                       max(RETRY_AFTER_MIN_S,
                           math.ceil(self._predicted_wait_s(g)))))

    # ---------------------------------------------------------- counting

    def _emit_admitted(self, klass: str, wait_ns: int) -> None:
        try:
            self.stats.count_with_tags("admission.admitted", 1, 1.0,
                                       [f"class:{klass}"])
            if wait_ns:
                self.stats.with_tags(f"class:{klass}").timing(
                    "admission.queue_wait", wait_ns)
        except Exception:  # noqa: BLE001 — telemetry never leaks slots
            pass

    def _emit_shed(self, klass: str, reason: str) -> None:
        try:
            if reason == "expired":
                self.stats.count_with_tags("admission.expired", 1, 1.0,
                                           [f"class:{klass}"])
            else:
                self.stats.count_with_tags(
                    "admission.shed", 1, 1.0,
                    [f"class:{klass}", f"reason:{reason}"])
        except Exception:  # noqa: BLE001 — telemetry never masks sheds
            pass

    def count_expired(self, klass: str) -> None:
        """An admitted request that expired DURING execution (the
        executor's deadline checks fired) — same counter, so
        ``admission.expired`` is the complete expiry picture."""
        g = self._gates.get(klass)
        if g is None:
            return
        with self._lock:
            g.expired += 1
        self._emit_shed(klass, "expired")

    # ------------------------------------------------------------- views

    def debug(self) -> dict:
        """The /debug/admission document.  With [tenants] isolation
        enabled each class carries its per-tenant queue/quota
        breakdown — the triage surface for "which tenant is eating
        the class"."""
        pol = _tenant.policy()
        with self._lock:
            out = {
                "enabled": self.enabled,
                "defaultDeadline": self.default_deadline,
                "classes": {
                    k: {
                        "cap": g.cap,
                        "queueDepth": g.depth,
                        "inFlight": g.in_flight,
                        "waiting": (len(g.waiters) + g.waiting_total),
                        "ewmaServiceMs": round(g.ewma_service_s * 1e3, 3),
                        "admitted": g.admitted,
                        "shed": g.shed,
                        "expired": g.expired,
                    }
                    for k, g in self._gates.items()
                },
            }
            if pol is not None:
                for k, g in self._gates.items():
                    out["classes"][k]["tenants"] = {
                        name: self._tenant_dict_locked(ts,
                                                       pol.quota_for(name))
                        for name, ts in g.tenants.items()
                    }
        if pol is not None:
            out["tenantsEnabled"] = True
        return out

    @staticmethod
    def _tenant_dict_locked(ts: _TenantState, quota) -> dict:
        return {
            "share": quota.share,
            "queueDepth": quota.queue,
            "inFlight": ts.in_flight,
            "waiting": len(ts.waiters),
            "deficit": round(ts.deficit, 3),
            "admitted": ts.admitted,
            "shed": ts.shed,
            "expired": ts.expired,
            "queueWaitEwmaMs": round(ts.wait_ewma_s * 1e3, 3),
        }

    def tenants_debug(self) -> dict:
        """Per-tenant totals aggregated across classes — the admission
        half of GET /debug/tenants (empty with isolation off AND no
        tenant state accrued)."""
        out: dict[str, dict] = {}
        with self._lock:
            for g in self._gates.values():
                for name, ts in g.tenants.items():
                    d = out.setdefault(name, {
                        "inFlight": 0, "waiting": 0, "admitted": 0,
                        "shed": 0, "expired": 0, "queueWaitEwmaMs": 0.0,
                    })
                    d["inFlight"] += ts.in_flight
                    d["waiting"] += len(ts.waiters)
                    d["admitted"] += ts.admitted
                    d["shed"] += ts.shed
                    d["expired"] += ts.expired
                    d["queueWaitEwmaMs"] = round(
                        max(d["queueWaitEwmaMs"],
                            ts.wait_ewma_s * 1e3), 3)
        return out
