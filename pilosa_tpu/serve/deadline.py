"""End-to-end request deadlines for the serving path.

A deadline is a *remaining budget* carried on the wire as the
``X-Pilosa-Deadline`` header (float seconds) — relative rather than an
absolute timestamp, so it survives clock skew between nodes: each hop
re-derives its own monotonic expiry from the remaining budget at
receive time (the same convention gRPC uses for its timeout header).

The handler parses the header into a :class:`Deadline` and installs it
for the request's scope (:class:`scope`); the executor carries it in
``ExecOptions`` and checks it at the translate, per-shard-map, and
reduce boundaries so expired work never reaches device dispatch; the
coalescer drops expired batch entries before launch; and the internal
client re-serializes the remaining budget onto outbound RPC so remote
sub-queries inherit the originating request's budget.

Deadline expiry raises :class:`DeadlineExceededError`, which the HTTP
layer maps to 503 with an ``expired`` outcome on the query's flight
record (pilosa_tpu.observe).
"""

from __future__ import annotations

import math
import threading
import time

#: Wire header carrying the remaining budget in seconds (float).
HEADER = "X-Pilosa-Deadline"

#: Budgets above this clamp down — a 25-hour deadline is a typo, and an
#: unbounded one would defeat the queue-wait arithmetic in admission.
MAX_BUDGET_S = 86400.0

_tls = threading.local()  # .dl: the Deadline active on this thread


class DeadlineExceededError(Exception):
    """The request's deadline expired before (or during) execution.
    Deliberately NOT a ValueError/ExecutionError subclass: the HTTP
    layer must map it to 503, not the 400 client-error bucket."""


class Deadline:
    """A monotonic expiry derived from a remaining budget."""

    __slots__ = ("budget_s", "expires_mono")

    def __init__(self, budget_s: float):
        self.budget_s = budget_s
        self.expires_mono = time.monotonic() + budget_s

    def remaining(self) -> float:
        """Seconds left; negative once expired."""
        return self.expires_mono - time.monotonic()

    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def __repr__(self) -> str:  # debug surfaces only
        return f"Deadline(remaining={self.remaining():.3f}s)"


def parse_header(value: str) -> Deadline:
    """``X-Pilosa-Deadline`` value -> Deadline.  Raises ValueError on a
    malformed value (the handler maps that to 400).  Zero or negative
    budgets are VALID — they mean "already expired" and shed
    immediately with an ``expired`` outcome, which lets callers whose
    budget ran out mid-retry still get an honest signal."""
    budget = float(value)  # ValueError propagates
    if not math.isfinite(budget):
        raise ValueError(f"non-finite deadline: {value!r}")
    return Deadline(min(budget, MAX_BUDGET_S))


def current() -> Deadline | None:
    """The deadline active on THIS thread, or None."""
    return getattr(_tls, "dl", None)


class tls_scope:
    """Re-entrant save/set/restore of one attribute on a
    threading.local — the shared base of every per-request scope
    (deadline.scope here, admission.rpc_class, observe.attach and
    observe.admission_scope).  ``__enter__`` returns the installed
    value; ``__exit__`` restores whatever was active before, so nested
    scopes shadow rather than clobber."""

    __slots__ = ("_tls_obj", "_attr", "value", "_prev")

    def __init__(self, tls_obj, attr: str, value):
        self._tls_obj = tls_obj
        self._attr = attr
        self.value = value

    def __enter__(self):
        self._prev = getattr(self._tls_obj, self._attr, None)
        setattr(self._tls_obj, self._attr, self.value)
        return self.value

    def __exit__(self, *exc):
        setattr(self._tls_obj, self._attr, self._prev)
        return False


class scope(tls_scope):
    """Install a deadline (or None) as this thread's active deadline
    for a with-block (re-entrant; see tls_scope)."""

    __slots__ = ()

    def __init__(self, dl: Deadline | None):
        super().__init__(_tls, "dl", dl)


def check(dl: Deadline | None, where: str) -> None:
    """Raise DeadlineExceededError when ``dl`` exists and has expired —
    the single check the executor sprinkles at its stage boundaries."""
    if dl is not None and dl.expired():
        raise DeadlineExceededError(f"deadline expired before {where}")
