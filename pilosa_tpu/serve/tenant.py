"""Per-tenant isolation: the process-wide [tenants] policy and the
thread-local tenant identity every shared resource charges against.

The reference's serving model has no notion of who a query belongs to
— one shared executor map-reduces every tenant's PQL over the same
shard pool (executor.go:2455), so one flooding client degrades
everyone.  ROADMAP item 5 names the gap ("per-tenant admission quotas
and result-cache budgets ... one abusive tenant can't evict or starve
the rest"); this module is the policy half of the fix:

- **Identity** — a tenant id rides the ``X-Pilosa-Tenant`` header (or
  ``?tenant=`` for tools), handler -> api -> ``ExecOptions.tenant`` ->
  executor, forwarded on node-to-node sub-queries exactly like
  ``?nocache``.  Requests with no id resolve to :data:`DEFAULT_TENANT`
  (the default tier).  The executor installs the id as a thread-local
  :class:`scope`, re-installed on map workers like the flight record,
  so the result cache and the residency manager can attribute bytes
  without threading a parameter through every call site.
- **Policy** — a :class:`TenantQuota` per configured tenant (plus a
  default tier for unknown ones): ``share`` is both the tenant's
  concurrency slots inside each admission class and its deficit-
  round-robin dequeue weight (serve/admission.py); ``queue`` bounds
  its per-class wait queue; ``cache_share`` / ``residency_share`` are
  the tenant's soft fraction of the result-cache byte budget and its
  HBM/host-tier residency quota (runtime/resultcache.py,
  runtime/residency.py).
- **Default-off** — ``[tenants] enabled = false`` (the default) keeps
  every enforcement site on its exact pre-tenant path
  (:func:`policy` returns None and the hot paths never touch tenant
  state), so a config with no ``[tenants]`` table is byte-identical
  to today's behavior — regression-pinned in tests/test_tenants.py.

Process-wide configuration mirrors ``[mesh]``: ``configure`` applies
explicit values in place, the FIRST server to ``retain()`` captures
the pre-server baseline and the LAST ``release()`` restores it
(pilosa-lint P5).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from pilosa_tpu.serve.deadline import tls_scope as _tls_scope

#: The tier every request with no tenant id (and every id without its
#: own ``[tenants.quotas.*]`` entry when ``strict`` naming is not a
#: thing we do) charges against.
DEFAULT_TENANT = "default"

#: Tenant ids are operator-facing labels, not payloads: cap the length
#: so a hostile header cannot grow per-tenant tables without bound.
MAX_TENANT_LEN = 64

#: Bound on DISTINCT unconfigured labels the policy individuates per
#: process.  The header is client-asserted, so a client rotating
#: arbitrary labels (a1, a2, a3, ...) would otherwise mint a fresh
#: default-tier quota — and a fresh admission/cache/residency state
#: entry — per label, multiplying its capacity by the rotation width
#: and growing per-tenant tables without bound.  Past the cap, new
#: unconfigured labels collapse into the shared default tier: they
#: still serve, they just share one quota.  Configured tenants are
#: never collapsed.
MAX_TRACKED_TENANTS = 256


@dataclass(frozen=True)
class TenantQuota:
    """One tenant's resource quotas.

    ``share`` — concurrency slots inside EACH admission class, and the
    tenant's deficit-round-robin weight when queued slots free up.
    ``queue`` — bounded wait-queue depth inside each class; an arrival
    past it sheds 429 ``tenant-queue-full`` (the "I am over quota"
    signal, distinct from the class-wide ``queue-full``).
    ``cache_share`` — soft fraction of the result-cache byte budget;
    past it, LRU eviction prefers this tenant's own entries.
    ``residency_share`` — fraction of the HBM (and host-tier) budget
    this tenant's stacks may hold before its own coldest stacks
    demote — an abusive working set demotes itself, not the zipfian
    head."""

    share: int = 4
    queue: int = 16
    cache_share: float = 0.25
    residency_share: float = 0.5


class TenantsRuntimeConfig:
    """The process-wide [tenants] knobs (one per process, like the
    [mesh] runtime config)."""

    __slots__ = ("enabled", "default_quota", "quotas", "seen")

    def __init__(self) -> None:
        self.enabled = False
        self.default_quota = TenantQuota()
        self.quotas: dict[str, TenantQuota] = {}
        # distinct UNCONFIGURED labels individuated so far (bounded by
        # MAX_TRACKED_TENANTS; set.add is atomic under the GIL, and a
        # lost race merely individuates one extra label)
        self.seen: set[str] = set()

    def quota_for(self, name: str) -> TenantQuota:
        return self.quotas.get(name, self.default_quota)

    def account(self, name: str) -> str:
        """The accounting identity for ``name``: itself while it is
        configured, already individuated, or within the individuation
        bound — else the shared :data:`DEFAULT_TENANT` tier."""
        if name == DEFAULT_TENANT or name in self.quotas \
                or name in self.seen:
            return name
        if len(self.seen) >= MAX_TRACKED_TENANTS:
            return DEFAULT_TENANT
        self.seen.add(name)
        return name


_cfg = TenantsRuntimeConfig()
_cfg_lock = threading.Lock()
_baseline: tuple | None = None
_refs = 0


def config() -> TenantsRuntimeConfig:
    return _cfg


def policy() -> TenantsRuntimeConfig | None:
    """The enforcement gate every per-tenant site consults: the config
    while [tenants] is enabled, else None — one attribute read on the
    disabled hot path, so default-config behavior stays byte-identical
    to pre-tenant code."""
    return _cfg if _cfg.enabled else None


def enabled() -> bool:
    return _cfg.enabled


def _coerce_quota(raw) -> TenantQuota:
    if isinstance(raw, TenantQuota):
        return raw
    if not isinstance(raw, dict):
        raise ValueError(f"tenant quota must be a table, got {raw!r}")
    d = {k.replace("-", "_"): v for k, v in raw.items()}
    unknown = set(d) - {"share", "queue", "cache_share",
                        "residency_share"}
    if unknown:
        raise ValueError(
            f"unknown tenant quota keys: {sorted(unknown)} "
            "(share, queue, cache-share, residency-share)")
    base = TenantQuota()
    q = TenantQuota(
        share=int(d.get("share", base.share)),
        queue=int(d.get("queue", base.queue)),
        cache_share=float(d.get("cache_share", base.cache_share)),
        residency_share=float(d.get("residency_share",
                                    base.residency_share)))
    if q.share < 1 or q.queue < 0:
        raise ValueError(f"tenant quota out of range: {q}")
    return q


def parse_quota_spec(spec: str) -> dict[str, TenantQuota]:
    """Compact quota spec for the CLI/env surface:
    ``name:share[:queue[:cache_share[:residency_share]]]`` entries,
    comma-separated — ``gold:16:64:0.5,free:2:8``."""
    out: dict[str, TenantQuota] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        if len(bits) < 2 or not bits[0]:
            raise ValueError(
                f"bad tenant quota entry {part!r} "
                "(name:share[:queue[:cache_share[:residency_share]]])")
        base = TenantQuota()
        out[bits[0]] = _coerce_quota({
            "share": int(bits[1]),
            "queue": int(bits[2]) if len(bits) > 2 else base.queue,
            "cache_share": (float(bits[3]) if len(bits) > 3
                            else base.cache_share),
            "residency_share": (float(bits[4]) if len(bits) > 4
                                else base.residency_share)})
    return out


def configure(enabled: bool | None = None,
              default_share: int | None = None,
              default_queue: int | None = None,
              default_cache_share: float | None = None,
              default_residency_share: float | None = None,
              quotas: dict | None = None) -> TenantsRuntimeConfig:
    """Apply [tenants] config in place — only explicit values land
    (the containers.configure contract).  ``quotas`` maps tenant name
    to a quota table/:class:`TenantQuota` and REPLACES the configured
    set (per-tenant quotas are one coherent table, not a merge)."""
    parsed = (None if quotas is None
              else {str(n): _coerce_quota(q) for n, q in quotas.items()})
    with _cfg_lock:
        if enabled is not None:
            _cfg.enabled = bool(enabled)
        d = _cfg.default_quota
        _cfg.default_quota = TenantQuota(
            share=int(default_share) if default_share is not None
            else d.share,
            queue=int(default_queue) if default_queue is not None
            else d.queue,
            cache_share=float(default_cache_share)
            if default_cache_share is not None else d.cache_share,
            residency_share=float(default_residency_share)
            if default_residency_share is not None
            else d.residency_share)
        if _cfg.default_quota.share < 1 or _cfg.default_quota.queue < 0:
            raise ValueError(
                f"default tenant quota out of range: {_cfg.default_quota}")
        if parsed is not None:
            _cfg.quotas = parsed
    return _cfg


def retain() -> None:
    """Take a server reference; the FIRST holder snapshots the
    pre-server baseline config (restore composes correctly under any
    close order — the PR-6 [ingest] lesson, pilosa-lint P5)."""
    global _refs, _baseline
    with _cfg_lock:
        if _refs == 0 and _baseline is None:
            _baseline = (_cfg.enabled, _cfg.default_quota,
                         dict(_cfg.quotas))
        _refs += 1


def release() -> None:
    """Drop a server reference; the LAST holder restores the baseline."""
    global _refs, _baseline
    with _cfg_lock:
        if _refs > 0:
            _refs -= 1
        if _refs == 0 and _baseline is not None:
            _cfg.enabled, _cfg.default_quota = _baseline[0], _baseline[1]
            _cfg.quotas = dict(_baseline[2])
            _cfg.seen = set()
            _baseline = None


def reset() -> TenantsRuntimeConfig:
    """Replace the process-wide config (tests)."""
    global _cfg, _baseline, _refs
    with _cfg_lock:
        _cfg = TenantsRuntimeConfig()
        _baseline = None
        _refs = 0
        return _cfg


# --------------------------------------------------------- identity


def clean(raw: str | None) -> str | None:
    """Normalize a wire-supplied tenant id: stripped, length-capped,
    empty -> None.  Never raises — a malformed label degrades to the
    default tier, not a 400 (the id is an accounting key, not a
    credential)."""
    if raw is None:
        return None
    t = str(raw).strip()
    if not t:
        return None
    return t[:MAX_TENANT_LEN]


def resolve(tenant: str | None) -> str:
    """The accounting identity for a request: its tenant id, or the
    default tier for anonymous ones.  While [tenants] is enabled the
    id also passes the individuation bound (``account``) so rotated
    arbitrary labels cannot mint unbounded per-tenant quotas."""
    name = tenant if tenant else DEFAULT_TENANT
    pol = policy()
    return pol.account(name) if pol is not None else name


_tls = threading.local()  # .tenant: active tenant id on this thread


class scope(_tls_scope):
    """Install a tenant id as this thread's identity for a scope
    (executor.execute installs the request's; _local_map re-installs
    on pool workers).  Re-entrant, like observe.attach."""

    __slots__ = ()

    def __init__(self, tenant: str | None):
        super().__init__(_tls, "tenant", tenant)


def current() -> str | None:
    """The tenant id active on THIS thread, or None."""
    return getattr(_tls, "tenant", None)


# ------------------------------------------------------------ gauges


def publish_gauges(stats, admission=None) -> None:
    """tenant.* gauge family for /metrics and /debug/vars — published
    unconditionally (zeros while [tenants] is off) so the family is
    scrape-visible before the first isolated tenant.  Cumulative
    totals render as gauges, never ALSO as counts (the cache.* rule)."""
    from pilosa_tpu.runtime import residency as _residency
    from pilosa_tpu.runtime import resultcache as _resultcache

    stats.gauge("tenant.enabled", 1 if _cfg.enabled else 0)
    stats.gauge("tenant.configured", len(_cfg.quotas))
    admitted = shed = expired = waiting = in_flight = 0
    known: set[str] = set()
    if admission is not None:
        for name, d in admission.tenants_debug().items():
            known.add(name)
            admitted += d["admitted"]
            shed += d["shed"]
            expired += d["expired"]
            waiting += d["waiting"]
            in_flight += d["inFlight"]
    cache_bytes = 0
    for name, d in _resultcache.cache().tenant_stats().items():
        known.add(name)
        cache_bytes += d["bytes"]
    res_bytes = host_bytes = 0
    for name, d in _residency.manager().tenant_stats().items():
        known.add(name)
        res_bytes += d["hbmBytes"]
        host_bytes += d["hostBytes"]
    stats.gauge("tenant.known", len(known))
    stats.gauge("tenant.admitted", admitted)
    stats.gauge("tenant.shed", shed)
    stats.gauge("tenant.expired", expired)
    stats.gauge("tenant.waiting", waiting)
    stats.gauge("tenant.in_flight", in_flight)
    stats.gauge("tenant.cache_bytes", cache_bytes)
    stats.gauge("tenant.residency_bytes", res_bytes)
    stats.gauge("tenant.residency_host_bytes", host_bytes)
