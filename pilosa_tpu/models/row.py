"""Row: a query-result bitmap spanning shards.

Parity with the reference's Row/rowSegment (row.go:27,332): results are
kept as one packed-word segment per shard; set algebra distributes over
segments and cross-node/cross-shard merge is a per-shard union.  Segments
live host-side as numpy uint32 words — per-shard compute stays on device
inside the executor and materializes here at reduce time.
"""

from __future__ import annotations

import numpy as np

from pilosa_tpu.ops import bitmap as bm
from pilosa_tpu.shardwidth import SHARD_WIDTH


class Row:
    __slots__ = ("segments", "attrs", "keys", "exclude_columns",
                 "wants_column_attrs")

    def __init__(self, segments: dict[int, np.ndarray] | None = None):
        # shard -> uint32[SHARD_WIDTH/32]
        self.segments: dict[int, np.ndarray] = segments or {}
        self.attrs: dict = {}
        self.keys: list[str] = []
        # serialization directives set by Options()/query params
        # (reference execOptions excludeColumns/columnAttrs)
        self.exclude_columns = False
        self.wants_column_attrs = False

    # -- constructors -------------------------------------------------------

    @classmethod
    def from_columns(cls, columns) -> "Row":
        row = cls()
        for col in columns:
            row.set(int(col))
        return row

    def set(self, col: int) -> None:
        shard, off = divmod(col, SHARD_WIDTH)
        seg = self.segments.get(shard)
        if seg is None:
            seg = np.zeros(bm.n_words(SHARD_WIDTH), dtype=np.uint32)
            self.segments[shard] = seg
        seg[off // bm.WORD_BITS] |= np.uint32(1) << np.uint32(off % bm.WORD_BITS)

    # -- set algebra (host reduce path) -------------------------------------

    def _binary(self, other: "Row", fn, keep_left=False, keep_right=False) -> "Row":
        out: dict[int, np.ndarray] = {}
        shards = set(self.segments)
        if keep_right:
            shards |= set(other.segments)
        elif not keep_left:
            shards &= set(other.segments)
        zeros = None
        for s in shards:
            a = self.segments.get(s)
            b = other.segments.get(s)
            if a is None or b is None:
                if zeros is None:
                    zeros = np.zeros(bm.n_words(SHARD_WIDTH), dtype=np.uint32)
                a = a if a is not None else zeros
                b = b if b is not None else zeros
            out[s] = fn(a, b)
        return Row(out)

    def intersect(self, other: "Row") -> "Row":
        return self._binary(other, np.bitwise_and)

    def union(self, other: "Row") -> "Row":
        return self._binary(other, np.bitwise_or, keep_right=True, keep_left=True)

    def difference(self, other: "Row") -> "Row":
        return self._binary(
            other, lambda a, b: a & ~b, keep_left=True
        )

    def xor(self, other: "Row") -> "Row":
        return self._binary(other, np.bitwise_xor, keep_right=True, keep_left=True)

    def merge(self, other: "Row") -> None:
        """In-place union; cross-node reduce (row.go Merge)."""
        for s, seg in other.segments.items():
            mine = self.segments.get(s)
            self.segments[s] = seg.copy() if mine is None else (mine | seg)

    # -- introspection ------------------------------------------------------

    def count(self) -> int:
        return sum(int(np.bitwise_count(seg).sum()) for seg in self.segments.values())

    def any(self) -> bool:
        return any(seg.any() for seg in self.segments.values())

    def columns(self) -> np.ndarray:
        """Sorted absolute column ids."""
        parts = []
        for s in sorted(self.segments):
            pos = bm.unpack_positions(self.segments[s])
            parts.append(pos + s * SHARD_WIDTH)
        if not parts:
            return np.empty(0, dtype=np.int64)
        return np.concatenate(parts)

    def shard_segment(self, shard: int) -> np.ndarray | None:
        return self.segments.get(shard)

    def intersection_count(self, other: "Row") -> int:
        total = 0
        for s, seg in self.segments.items():
            o = other.segments.get(s)
            if o is not None:
                total += int(np.bitwise_count(seg & o).sum())
        return total

    def __eq__(self, other) -> bool:
        if not isinstance(other, Row):
            return NotImplemented
        return np.array_equal(self.columns(), other.columns())

    def __repr__(self) -> str:
        cols = self.columns()
        head = ", ".join(str(c) for c in cols[:8])
        more = "..." if len(cols) > 8 else ""
        return f"Row([{head}{more}] n={len(cols)})"
