"""Attribute storage: arbitrary key->value metadata on rows and columns.

Parity with the reference's AttrStore (attr.go:34) and its BoltDB
implementation (boltdb/attrstore.go): merge-on-write semantics, bulk set,
and 100-id attribute blocks with checksums for anti-entropy diffing
(attr.go:80-120).  Backed by sqlite (stdlib) instead of BoltDB.
"""

from __future__ import annotations

import hashlib
import json
import sqlite3
import threading
from collections import OrderedDict

# Attribute block size for anti-entropy diffs (reference attrBlockSize,
# attr.go:80).
ATTR_BLOCK_SIZE = 100

#: read-cache entries per store (reference attrCacheSize LRU in front
#: of BoltDB, attr.go:80) — hot TopN attr-filter scans must not hit
#: SQLite per row
ATTR_CACHE_SIZE = 8192


class AttrStore:
    def __init__(self, path: str | None = None):
        self.path = path or ":memory:"
        self._lock = threading.RLock()
        # One shared connection for all threads (an in-memory sqlite DB is
        # per-connection, so thread-local connections would each see an
        # empty database); every access is serialized by self._lock.
        self._db = sqlite3.connect(self.path, check_same_thread=False)
        with self._lock, self._db as c:
            c.execute(
                "CREATE TABLE IF NOT EXISTS attrs (id INTEGER PRIMARY KEY, data TEXT)"
            )
        # LRU read cache (attr.go:80) holding the JSON STRING exactly
        # as stored ("" = id absent, so hot attr-less rows skip SQLite
        # too).  Caching the string rather than the parsed dict makes
        # every read an independent json.loads — no shared mutable
        # values, so a caller mutating its result (even nested lists)
        # can never poison the cache.  Writes update the entry with
        # the dump they computed anyway.
        self._cache: OrderedDict[int, str] = OrderedDict()
        self.cache_hits = 0
        self.cache_misses = 0

    def _conn(self) -> sqlite3.Connection:
        return self._db

    def _cache_put(self, id_: int, data: str) -> None:
        # under self._lock
        self._cache[id_] = data
        self._cache.move_to_end(id_)
        while len(self._cache) > ATTR_CACHE_SIZE:
            self._cache.popitem(last=False)

    def _data_locked(self, id_: int) -> str:
        """Cached JSON string for one id ("" = absent); under
        self._lock.  Counter-free — set_attrs' read-modify-write goes
        through here so the hit/miss counters track READ traffic only
        (they exist to size ATTR_CACHE_SIZE)."""
        hit = self._cache.get(id_)
        if hit is not None:
            self._cache.move_to_end(id_)
            return hit
        cur = self._conn().execute("SELECT data FROM attrs WHERE id=?",
                                   (id_,))
        row = cur.fetchone()
        data = row[0] if row else ""
        self._cache_put(id_, data)
        return data

    def attrs(self, id_: int) -> dict:
        with self._lock:
            cached = id_ in self._cache
            self.cache_hits += cached
            self.cache_misses += not cached
            data = self._data_locked(id_)
        return json.loads(data) if data else {}

    def set_attrs(self, id_: int, attrs: dict) -> None:
        """Merge attrs into existing; None values delete keys (reference
        SetAttrs merge semantics, boltdb/attrstore.go:120)."""
        with self._lock:
            data = self._data_locked(id_)
            cur = json.loads(data) if data else {}
            for k, v in attrs.items():
                if v is None:
                    cur.pop(k, None)
                else:
                    cur[k] = v
            dumped = json.dumps(cur, sort_keys=True)
            with self._db as c:
                c.execute(
                    "INSERT OR REPLACE INTO attrs (id, data) VALUES (?, ?)",
                    (id_, dumped),
                )
            self._cache_put(id_, dumped)

    def attrs_bulk(self, ids) -> dict[int, dict]:
        """Batched lookup: cache hits first, then one IN-query per 500
        missing ids (the per-id form would hold the store lock once per
        column on columnAttrs responses); misses populate the cache."""
        ids = [int(i) for i in dict.fromkeys(ids)]  # dedupe, keep order
        out: dict[int, dict] = {}
        with self._lock:
            missing = []
            for id_ in ids:
                hit = self._cache.get(id_)
                if hit is not None:
                    self._cache.move_to_end(id_)
                    self.cache_hits += 1
                    if hit:  # attr-less ids stay absent, as before
                        out[id_] = json.loads(hit)
                else:
                    missing.append(id_)
            self.cache_misses += len(missing)
            con = self._conn()
            found = {}
            for i in range(0, len(missing), 500):
                chunk = missing[i:i + 500]
                cur = con.execute(
                    "SELECT id, data FROM attrs WHERE id IN "
                    f"({','.join('?' * len(chunk))})", chunk)
                for id_, data in cur.fetchall():
                    found[int(id_)] = data
            for id_ in missing:
                data = found.get(id_, "")
                self._cache_put(id_, data)
                if data:
                    out[id_] = json.loads(data)
        return out

    def set_bulk_attrs(self, attrs_by_id: dict[int, dict]) -> None:
        for id_, attrs in sorted(attrs_by_id.items()):
            self.set_attrs(id_, attrs)

    def ids(self) -> list[int]:
        with self._lock:
            cur = self._conn().execute("SELECT id FROM attrs ORDER BY id")
            return [r[0] for r in cur.fetchall()]

    # ---- anti-entropy blocks (reference attr.go:80-120) ----

    def blocks(self) -> list[tuple[int, bytes]]:
        """(block id, checksum) per 100-id block of attribute data."""
        out: list[tuple[int, bytes]] = []
        h = None
        cur_block = None
        with self._lock:
            rows = self._conn().execute(
                "SELECT id, data FROM attrs ORDER BY id"
            ).fetchall()
        for id_, data in rows:
            blk = id_ // ATTR_BLOCK_SIZE
            if blk != cur_block:
                if cur_block is not None:
                    out.append((cur_block, h.digest()))
                cur_block, h = blk, hashlib.blake2b(digest_size=16)
            h.update(str(id_).encode())
            h.update(data.encode())
        if cur_block is not None:
            out.append((cur_block, h.digest()))
        return out

    def block_data(self, block: int) -> dict[int, dict]:
        lo, hi = block * ATTR_BLOCK_SIZE, (block + 1) * ATTR_BLOCK_SIZE
        with self._lock:
            rows = self._conn().execute(
                "SELECT id, data FROM attrs WHERE id >= ? AND id < ? ORDER BY id",
                (lo, hi),
            ).fetchall()
        return {r[0]: json.loads(r[1]) for r in rows}

    def blocks_diff(self, other_blocks: list[tuple[int, bytes]]) -> list[int]:
        """Block ids whose checksums differ from a peer's (reference
        attrBlocks.Diff, attr.go:90)."""
        mine = dict(self.blocks())
        theirs = dict(other_blocks)
        return sorted(
            set(b for b in mine if mine[b] != theirs.get(b))
            | set(b for b in theirs if theirs[b] != mine.get(b))
        )

    def close(self) -> None:
        with self._lock:
            self._db.close()
