"""Attribute storage: arbitrary key->value metadata on rows and columns.

Parity with the reference's AttrStore (attr.go:34) and its BoltDB
implementation (boltdb/attrstore.go): merge-on-write semantics, bulk set,
and 100-id attribute blocks with checksums for anti-entropy diffing
(attr.go:80-120).  Backed by sqlite (stdlib) instead of BoltDB.
"""

from __future__ import annotations

import hashlib
import json
import sqlite3
import threading

# Attribute block size for anti-entropy diffs (reference attrBlockSize,
# attr.go:80).
ATTR_BLOCK_SIZE = 100


class AttrStore:
    def __init__(self, path: str | None = None):
        self.path = path or ":memory:"
        self._lock = threading.RLock()
        # One shared connection for all threads (an in-memory sqlite DB is
        # per-connection, so thread-local connections would each see an
        # empty database); every access is serialized by self._lock.
        self._db = sqlite3.connect(self.path, check_same_thread=False)
        with self._lock, self._db as c:
            c.execute(
                "CREATE TABLE IF NOT EXISTS attrs (id INTEGER PRIMARY KEY, data TEXT)"
            )

    def _conn(self) -> sqlite3.Connection:
        return self._db

    def attrs(self, id_: int) -> dict:
        with self._lock:
            cur = self._conn().execute("SELECT data FROM attrs WHERE id=?", (id_,))
            row = cur.fetchone()
        return json.loads(row[0]) if row else {}

    def set_attrs(self, id_: int, attrs: dict) -> None:
        """Merge attrs into existing; None values delete keys (reference
        SetAttrs merge semantics, boltdb/attrstore.go:120)."""
        with self._lock:
            cur = self.attrs(id_)
            for k, v in attrs.items():
                if v is None:
                    cur.pop(k, None)
                else:
                    cur[k] = v
            with self._db as c:
                c.execute(
                    "INSERT OR REPLACE INTO attrs (id, data) VALUES (?, ?)",
                    (id_, json.dumps(cur, sort_keys=True)),
                )

    def attrs_bulk(self, ids) -> dict[int, dict]:
        """Batched lookup: one IN-query per 500 ids (the per-id form
        would hold the store lock once per column on columnAttrs
        responses)."""
        ids = [int(i) for i in ids]
        out: dict[int, dict] = {}
        with self._lock:
            con = self._conn()
            for i in range(0, len(ids), 500):
                chunk = ids[i:i + 500]
                cur = con.execute(
                    "SELECT id, data FROM attrs WHERE id IN "
                    f"({','.join('?' * len(chunk))})", chunk)
                for id_, data in cur.fetchall():
                    out[int(id_)] = json.loads(data)
        return out

    def set_bulk_attrs(self, attrs_by_id: dict[int, dict]) -> None:
        for id_, attrs in sorted(attrs_by_id.items()):
            self.set_attrs(id_, attrs)

    def ids(self) -> list[int]:
        with self._lock:
            cur = self._conn().execute("SELECT id FROM attrs ORDER BY id")
            return [r[0] for r in cur.fetchall()]

    # ---- anti-entropy blocks (reference attr.go:80-120) ----

    def blocks(self) -> list[tuple[int, bytes]]:
        """(block id, checksum) per 100-id block of attribute data."""
        out: list[tuple[int, bytes]] = []
        h = None
        cur_block = None
        with self._lock:
            rows = self._conn().execute(
                "SELECT id, data FROM attrs ORDER BY id"
            ).fetchall()
        for id_, data in rows:
            blk = id_ // ATTR_BLOCK_SIZE
            if blk != cur_block:
                if cur_block is not None:
                    out.append((cur_block, h.digest()))
                cur_block, h = blk, hashlib.blake2b(digest_size=16)
            h.update(str(id_).encode())
            h.update(data.encode())
        if cur_block is not None:
            out.append((cur_block, h.digest()))
        return out

    def block_data(self, block: int) -> dict[int, dict]:
        lo, hi = block * ATTR_BLOCK_SIZE, (block + 1) * ATTR_BLOCK_SIZE
        with self._lock:
            rows = self._conn().execute(
                "SELECT id, data FROM attrs WHERE id >= ? AND id < ? ORDER BY id",
                (lo, hi),
            ).fetchall()
        return {r[0]: json.loads(r[1]) for r in rows}

    def blocks_diff(self, other_blocks: list[tuple[int, bytes]]) -> list[int]:
        """Block ids whose checksums differ from a peer's (reference
        attrBlocks.Diff, attr.go:90)."""
        mine = dict(self.blocks())
        theirs = dict(other_blocks)
        return sorted(
            set(b for b in mine if mine[b] != theirs.get(b))
            | set(b for b in theirs if theirs[b] != mine.get(b))
        )

    def close(self) -> None:
        with self._lock:
            self._db.close()
