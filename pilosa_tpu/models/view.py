"""View: one physical layout of a field, owning fragments by shard.

Parity with the reference's view (view.go:44): a field has a "standard"
view, time-quantum views named standard_YYYYMMDDHH etc., and BSI views
named bsig_<field> (view.go:37-41).  The view routes bits to the fragment
owning the column's shard and creates fragments on first write
(view.go:263 CreateFragmentIfNotExists).
"""

from __future__ import annotations

import os

import numpy as np

from pilosa_tpu.models.fragment import Fragment
from pilosa_tpu.shardwidth import SHARD_WIDTH

VIEW_STANDARD = "standard"
VIEW_BSI_PREFIX = "bsig_"


class View:
    def __init__(
        self,
        path: str | None,
        index: str,
        field: str,
        name: str,
        mutex: bool = False,
        cache_type: str = "ranked",
        cache_size: int = 50000,
    ):
        from pilosa_tpu import lockcheck as _lockcheck

        self.path = path
        self.index = index
        self.field = field
        self.name = name
        self.mutex = mutex
        self.cache_type = cache_type
        self.cache_size = cache_size
        self.fragments: dict[int, Fragment] = {}
        # guards fragment CREATION/DELETION only; reads stay lock-free
        # (GIL-atomic dict gets, the double-checked pattern)
        self._lock = _lockcheck.lock("view")
        if path is not None:
            os.makedirs(self._frag_dir, exist_ok=True)
            self._open_fragments()

    @property
    def _frag_dir(self) -> str:
        return os.path.join(self.path, "fragments")

    def _frag_path(self, shard: int) -> str:
        return os.path.join(self._frag_dir, str(shard))

    def _open_fragments(self) -> None:
        seen = set()
        for fn in os.listdir(self._frag_dir):
            base = fn.rsplit(".", 1)[0]
            if base.isdigit():
                seen.add(int(base))
        for shard in sorted(seen):
            self.fragments[shard] = Fragment(
                self._frag_path(shard), self.index, self.field, self.name,
                shard, mutex=self.mutex,
                cache_type=self.cache_type, cache_size=self.cache_size,
            )

    def fragment(self, shard: int) -> Fragment | None:
        return self.fragments.get(shard)

    def create_fragment_if_not_exists(self, shard: int) -> Fragment:
        """Create-on-first-write, double-checked under the view lock:
        two concurrent first-writers to a fresh shard must get the
        SAME Fragment object — the unlocked check-then-act let each
        construct its own, one won the dict, and the loser's
        acknowledged write landed in an orphaned object (found by the
        self-healing convergence soak: one bit silently missing on a
        replica after concurrent degraded-write ingest; with a path,
        both objects also held append handles to the same WAL file)."""
        frag = self.fragments.get(shard)
        if frag is not None:
            return frag
        with self._lock:
            frag = self.fragments.get(shard)
            if frag is None:
                path = (None if self.path is None
                        else self._frag_path(shard))
                frag = Fragment(
                    path, self.index, self.field, self.name, shard,
                    mutex=self.mutex,
                    cache_type=self.cache_type,
                    cache_size=self.cache_size,
                )
                self.fragments[shard] = frag
        return frag

    def delete_fragment(self, shard: int) -> bool:
        """Close and delete one shard's fragment and its files — the
        post-resize cleaner path (reference holderCleaner,
        holder.go:1126 cleanHolder; view.deleteFragment)."""
        with self._lock:
            frag = self.fragments.pop(shard, None)
        if frag is None:
            return False
        frag.close()
        if self.path is not None:
            base = self._frag_path(shard)
            for suffix in (".snap", ".wal", ".cache"):
                try:
                    os.remove(base + suffix)
                except FileNotFoundError:
                    pass
        return True

    def available_shards(self) -> set[int]:
        return set(self.fragments)

    # -- bit ops ------------------------------------------------------------

    def set_bit(self, row: int, col: int) -> bool:
        return self.create_fragment_if_not_exists(col // SHARD_WIDTH).set_bit(row, col)

    def clear_bit(self, row: int, col: int) -> bool:
        frag = self.fragment(col // SHARD_WIDTH)
        return False if frag is None else frag.clear_bit(row, col)

    def row(self, row_id: int, shard: int) -> np.ndarray | None:
        frag = self.fragment(shard)
        return None if frag is None else frag.row(row_id)

    # -- BSI ops ------------------------------------------------------------

    def set_value(self, col: int, depth: int, value: int) -> bool:
        return self.create_fragment_if_not_exists(col // SHARD_WIDTH).set_value(
            col, depth, value
        )

    def value(self, col: int, depth: int) -> tuple[int, bool]:
        frag = self.fragment(col // SHARD_WIDTH)
        return (0, False) if frag is None else frag.value(col, depth)

    # -- streaming ingest (pilosa_tpu.ingest) -------------------------------

    def flush_deltas(self) -> int:
        """Merge every fragment's pending delta plane into base state;
        returns bit positions merged (0 when nothing pended)."""
        return sum(frag.flush_delta()
                   for frag in list(self.fragments.values()))

    def delta_stats(self) -> dict:
        """Pending-delta audit for this view: per-shard delta stats
        (the /debug/ingest per-fragment section aggregates these)."""
        out = {}
        for shard, frag in list(self.fragments.items()):
            s = frag.delta_stats()
            if s is not None:
                out[shard] = s
        return out

    def close(self) -> None:
        for frag in self.fragments.values():
            frag.close()

    def snapshot(self) -> None:
        for frag in self.fragments.values():
            frag.snapshot()
