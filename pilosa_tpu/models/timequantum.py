"""Time quantum: decompose timestamps into per-granularity views.

Behavioral parity with the reference's time.go: a quantum is a subset of
"YMDH"; a write at time t lands in one view per enabled unit
(time.go:91 viewsByTime), and a range query computes the minimal set of
views covering [start, end) by walking up unit granularities then back
down (time.go:104-180 viewsByTimeRange).
"""

from __future__ import annotations

import datetime as _dt

VALID_QUANTUMS = {"Y", "YM", "YMD", "YMDH", "M", "MD", "MDH", "D", "DH", "H", ""}

# Reference wire format for timestamps (pilosa.go TimeFormat).
TIME_FORMAT = "%Y-%m-%dT%H:%M"


class TimeQuantum(str):
    """A time granularity string: subset of 'YMDH' in order."""

    def __new__(cls, value: str = ""):
        if value not in VALID_QUANTUMS:
            raise ValueError(f"invalid time quantum: {value!r}")
        return super().__new__(cls, value)

    @property
    def has_year(self) -> bool:
        return "Y" in self

    @property
    def has_month(self) -> bool:
        return "M" in self

    @property
    def has_day(self) -> bool:
        return "D" in self

    @property
    def has_hour(self) -> bool:
        return "H" in self


_UNIT_FMT = {"Y": "%Y", "M": "%Y%m", "D": "%Y%m%d", "H": "%Y%m%d%H"}


def view_by_time_unit(name: str, t: _dt.datetime, unit: str) -> str:
    """View name for one quantum unit, e.g. standard_2017 / standard_201701."""
    return f"{name}_{t.strftime(_UNIT_FMT[unit])}"


def views_by_time(name: str, t: _dt.datetime, q: TimeQuantum) -> list[str]:
    """All views a write at time t lands in (one per enabled unit)."""
    return [view_by_time_unit(name, t, u) for u in q]


def _add_month(t: _dt.datetime) -> _dt.datetime:
    # For day > 28, first snap to the 1st so adding a month never skips one
    # (the reference's addMonth edge case, time.go:180-189).
    if t.day > 28:
        t = t.replace(day=1)
    if t.month == 12:
        return t.replace(year=t.year + 1, month=1)
    return t.replace(month=t.month + 1)


def _add_year(t: _dt.datetime) -> _dt.datetime:
    return t.replace(year=t.year + 1)


def _next_year_gte(t: _dt.datetime, end: _dt.datetime) -> bool:
    nxt = _add_year(t)
    return nxt.year == end.year or end > nxt


def _next_month_gte(t: _dt.datetime, end: _dt.datetime) -> bool:
    nxt = _true_add_month(t)
    return (nxt.year, nxt.month) == (end.year, end.month) or end > nxt


def _true_add_month(t: _dt.datetime) -> _dt.datetime:
    # Go's AddDate(0,1,0) with normalization (Jan 31 + 1mo = Mar 2/3).
    y, m = t.year, t.month + 1
    if m > 12:
        y, m = y + 1, 1
    # days overflow normalizes into the following month, like Go.
    try:
        return t.replace(year=y, month=m)
    except ValueError:
        first = _dt.datetime(y, m, 1, t.hour, t.minute, t.second)
        return first + _dt.timedelta(days=t.day - 1)


def _next_day_gte(t: _dt.datetime, end: _dt.datetime) -> bool:
    nxt = t + _dt.timedelta(days=1)
    return (nxt.year, nxt.month, nxt.day) == (end.year, end.month, end.day) or end > nxt


def views_by_time_range(
    name: str, start: _dt.datetime, end: _dt.datetime, q: TimeQuantum
) -> list[str]:
    """Minimal view cover of [start, end): coarse views in the middle,
    fine views at the ragged edges (reference time.go:104-180)."""
    t = start
    results: list[str] = []

    # Walk up from smallest units to largest.
    if q.has_hour or q.has_day or q.has_month:
        while t < end:
            if q.has_hour:
                if not _next_day_gte(t, end):
                    break
                if t.hour != 0:
                    results.append(view_by_time_unit(name, t, "H"))
                    t += _dt.timedelta(hours=1)
                    continue
            if q.has_day:
                if not _next_month_gte(t, end):
                    break
                if t.day != 1:
                    results.append(view_by_time_unit(name, t, "D"))
                    t += _dt.timedelta(days=1)
                    continue
            if q.has_month:
                if not _next_year_gte(t, end):
                    break
                if t.month != 1:
                    results.append(view_by_time_unit(name, t, "M"))
                    t = _add_month(t)
                    continue
            break

    # Walk back down from largest units to smallest.
    while t < end:
        if q.has_year and _next_year_gte(t, end):
            results.append(view_by_time_unit(name, t, "Y"))
            t = _add_year(t)
        elif q.has_month and _next_month_gte(t, end):
            results.append(view_by_time_unit(name, t, "M"))
            t = _add_month(t)
        elif q.has_day and _next_day_gte(t, end):
            results.append(view_by_time_unit(name, t, "D"))
            t += _dt.timedelta(days=1)
        elif q.has_hour:
            results.append(view_by_time_unit(name, t, "H"))
            t += _dt.timedelta(hours=1)
        else:
            break

    return results


def parse_time(value) -> _dt.datetime:
    """Parse a PQL timestamp: 'YYYY-MM-DDTHH:MM' string or unix seconds int
    (reference time.go parseTime)."""
    if isinstance(value, _dt.datetime):
        return value
    if isinstance(value, str):
        try:
            return _dt.datetime.strptime(value, TIME_FORMAT)
        except ValueError as e:
            raise ValueError(f"cannot parse string time: {value!r}") from e
    if isinstance(value, int):
        return _dt.datetime.fromtimestamp(value, _dt.timezone.utc).replace(tzinfo=None)
    raise ValueError(f"cannot parse time from {type(value).__name__}")
