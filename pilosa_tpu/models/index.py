"""Index: a namespace of fields sharing one column space.

Parity with the reference's Index (index.go:37): options ``keys`` (string
key translation) and ``track_existence`` (maintains a hidden ``_exists``
field recording which columns have any data, index.go:214,530).
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass

from pilosa_tpu.models.field import Field, FieldOptions, validate_name

# Name of the hidden existence field (reference existenceFieldName,
# holder.go:46).
EXISTENCE_FIELD = "_exists"


@dataclass
class IndexOptions:
    keys: bool = False
    track_existence: bool = True

    def to_dict(self) -> dict:
        return {"keys": self.keys, "trackExistence": self.track_existence}

    @classmethod
    def from_dict(cls, d: dict) -> "IndexOptions":
        return cls(
            keys=d.get("keys", False),
            track_existence=d.get("trackExistence", True),
        )


class Index:
    def __init__(self, path: str | None, name: str, options: IndexOptions | None = None):
        validate_name(name)
        self.path = path
        self.name = name
        self.options = options or IndexOptions()
        self.fields: dict[str, Field] = {}
        self._lock = threading.RLock()
        if path is not None:
            os.makedirs(path, exist_ok=True)
            self._load_meta()
            self._open_fields()
        if self.options.track_existence and EXISTENCE_FIELD not in self.fields:
            self._create_existence_field()
        from pilosa_tpu.models.attrs import AttrStore

        self.column_attrs = AttrStore(
            None if path is None else os.path.join(path, ".column_attrs.db")
        )
        self._translate_store = None

    @property
    def translate_store(self):
        """Column-key translate store, opened lazily (reference
        index.go column translation via holder.translateFile)."""
        if self._translate_store is None:
            from pilosa_tpu.storage.translate import open_translate_store

            path = None if self.path is None else os.path.join(self.path, ".keys.db")
            self._translate_store = open_translate_store(path)
        return self._translate_store

    @property
    def _meta_path(self) -> str:
        return os.path.join(self.path, ".meta")

    def _load_meta(self) -> None:
        if os.path.exists(self._meta_path):
            with open(self._meta_path) as f:
                self.options = IndexOptions.from_dict(json.load(f))
        else:
            self.save_meta()

    def save_meta(self) -> None:
        if self.path is None:
            return
        from pilosa_tpu.ioutil import atomic_write_json

        atomic_write_json(self._meta_path, self.options.to_dict())

    def _open_fields(self) -> None:
        for name in sorted(os.listdir(self.path)):
            fdir = os.path.join(self.path, name)
            if os.path.isdir(fdir) and os.path.exists(os.path.join(fdir, ".meta")):
                self.fields[name] = self._adopt(
                    Field(fdir, self.name, name, FieldOptions()))

    def _create_existence_field(self) -> None:
        path = None if self.path is None else os.path.join(self.path, EXISTENCE_FIELD)
        self.fields[EXISTENCE_FIELD] = self._adopt(Field(
            path, self.name, EXISTENCE_FIELD, FieldOptions.set_field(cache_type="none")
        ))

    def _adopt(self, f: Field) -> Field:
        """Give the field a weak back-reference to its index — the
        prewarm worker needs the INDEX shard set (the fused executor
        keys stacks by ``sorted(index.available_shards())``,
        executor.py _target_shards), which the field alone can't see."""
        import weakref

        f._index_ref = weakref.ref(self)
        return f

    # -------------------------------------------------------------- fields

    def field(self, name: str) -> Field | None:
        return self.fields.get(name)

    def existence_field(self) -> Field | None:
        return self.fields.get(EXISTENCE_FIELD) if self.options.track_existence else None

    def create_field(self, name: str, options: FieldOptions | None = None) -> Field:
        with self._lock:
            if name in self.fields:
                raise ValueError(f"field already exists: {name}")
            return self._create_field(name, options or FieldOptions())

    def create_field_if_not_exists(self, name: str, options: FieldOptions | None = None) -> Field:
        with self._lock:
            f = self.fields.get(name)
            if f is not None:
                return f
            return self._create_field(name, options or FieldOptions())

    def _create_field(self, name: str, options: FieldOptions) -> Field:
        validate_name(name)
        path = None if self.path is None else os.path.join(self.path, name)
        f = self._adopt(Field(path, self.name, name, options))
        self.fields[name] = f
        return f

    def delete_field(self, name: str) -> None:
        with self._lock:
            f = self.fields.pop(name, None)
            if f is None:
                raise KeyError(f"field not found: {name}")
            f.close()
            if f.path is not None:
                import shutil

                shutil.rmtree(f.path, ignore_errors=True)

    def public_fields(self) -> list[Field]:
        return [f for n, f in sorted(self.fields.items()) if not n.startswith("_")]

    def import_existence(self, cols) -> None:
        """Record imported columns in the hidden existence field —
        bulk-import parity with the write path (reference
        api.go:968 importExistenceColumns; executor Set updates
        existence per bit)."""
        f = self.existence_field()
        if f is None or len(cols) == 0:  # len(): ndarray-safe
            return
        import numpy as np

        if isinstance(cols, np.ndarray):
            f.import_bits(np.zeros(len(cols), dtype=np.int64), cols)
        else:
            f.import_bits([0] * len(cols), list(cols))

    def all_fields(self) -> list[Field]:
        """Public + internal fields (``_exists``) — storage-walking code
        (resize, anti-entropy, cleanup) must cover both."""
        return [f for _, f in sorted(self.fields.items())]

    # -------------------------------------------------------------- shards

    def available_shards(self) -> set[int]:
        """Union of per-field shard sets (reference AvailableShards,
        index.go:292)."""
        shards: set[int] = set()
        for f in self.fields.values():
            shards |= f.available_shards()
        return shards

    def close(self) -> None:
        for f in self.fields.values():
            f.close()
        self.column_attrs.close()
        if self._translate_store is not None:
            self._translate_store.close()

    def snapshot(self) -> None:
        for f in self.fields.values():
            f.snapshot()
